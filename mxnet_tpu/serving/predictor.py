"""Predictor — a trained symbol bound for thread-safe, bucketed inference.

`BaseModule.predict` is a training-loop convenience: one caller, one eval
iterator, one bound batch shape — a concurrent, ragged request stream
through it either recompiles on every odd batch size or serializes callers
behind rebinds. The Predictor is the serving-side answer, composing two
pieces the training stack already proved out:

* **bucket-ladder executors** — one ``for_training=False`` executor per
  configured batch-size bucket (``MXNET_SERVING_BUCKETS``), every request
  padded up to the smallest bucket that fits via :func:`io.pad_arrays`
  (rows sliced back off the outputs, the partial-last-batch mechanism from
  the fused-step PR). Steady traffic therefore touches exactly
  ``len(buckets)`` compiled programs, no matter how ragged the sizes.
* **the named compile cache** — every bucket executable lives in ONE
  :class:`~mxnet_tpu.compile_cache.CompileCache` named ``"serving"``
  (shared across buckets; the per-executor cache is re-pointed at it), so
  warmup can pin the exact compile count and steady state can assert
  zero new misses (``compile.cache_hits/_misses`` counters, unconditional).

Weights are SHARED across bucket executors (the same NDArray objects are
bound into each), so N buckets cost N compiled programs but one copy of
the parameters. Inference never writes them.

Execution is serialized on one lock: a single device runs one computation
at a time — serving concurrency comes from batching (the
:class:`~mxnet_tpu.serving.batcher.DynamicBatcher`), not parallel dispatch.

Cross-bucket determinism note (pinned by test_serving.py): for row-
independent graphs, XLA:CPU produces bit-identical per-row results across
bucket sizes >= 2 and regardless of row position or padding; batch size 1
lowers to the vector codepath and can differ by 1 ulp. A ladder starting
at 2 gives bit-exact responses whether or not requests were coalesced.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import analysis
from .. import memory
from .. import ndarray as nd
from .. import observatory
from .. import telemetry
from .. import tracing
from ..base import MXNetError, getenv, register_env
from ..compile_cache import CompileCache
from ..io.io import DataDesc, pad_arrays

__all__ = ["Predictor", "bucket_ladder"]

register_env("MXNET_SERVING_BUCKETS", "1,2,4,8,16,32",
             "serving batch-size bucket ladder (comma-separated ints): "
             "every request/coalesced batch pads up to the smallest bucket "
             "that fits, so steady traffic reuses len(buckets) executables")
register_env("MXNET_SUBGRAPH_BACKEND", "TPU_FUSE",
             "subgraph rewrite backend auto-applied by Predictor.load / "
             "Predictor.from_module (conv+bn(+relu) folding for inference); "
             "set to NONE or 0 to opt out. Training-side bind only applies "
             "it when the variable is EXPLICITLY set (symbol.simple_bind "
             "semantics unchanged)")


def _serving_fused(symbol, arg_params, aux_params):
    """Apply the serving-side subgraph backend (default ``TPU_FUSE``,
    opt-out ``MXNET_SUBGRAPH_BACKEND=NONE``) to a checkpointed symbol and
    migrate parameters across the rewrite: BatchNorm moving statistics are
    *auxiliary* states of the original graph but plain *arguments* of the
    folded `_fused_conv_bn_relu` node, so they move from ``aux_params``
    into ``arg_params``. Returns (symbol, arg_params, aux_params) —
    unchanged when the backend is disabled, unregistered, or matches
    nothing."""
    import os

    backend = os.environ.get("MXNET_SUBGRAPH_BACKEND", "TPU_FUSE")
    if not backend or backend in ("NONE", "none", "0"):
        return symbol, arg_params, aux_params
    from ..symbol.subgraph import build_subgraph, list_subgraph_backends

    if backend not in list_subgraph_backends():
        return symbol, arg_params, aux_params
    fused = build_subgraph(symbol, backend)
    fused_args = set(fused.list_arguments())
    arg_params = dict(arg_params or {})
    aux_params = dict(aux_params or {})
    for name in list(aux_params):
        if name in fused_args and name not in arg_params:
            arg_params[name] = aux_params.pop(name)
    return fused, arg_params, aux_params


def bucket_ladder(buckets=None, env_var="MXNET_SERVING_BUCKETS"):
    """Normalize a bucket spec (None -> the ``env_var`` knob, a
    comma-separated string, or any int iterable) into an ascending,
    deduplicated tuple of positive sizes. ``env_var`` names the knob in
    error messages — the generation plane's ``prefill_ladder`` parses its
    ``MXNET_GENERATION_PREFILL_BUCKETS`` through here too."""
    if buckets is None:
        buckets = getenv(env_var)
    if isinstance(buckets, str):
        try:
            buckets = [int(tok) for tok in buckets.replace(" ", "").split(",")
                       if tok]
        except ValueError:
            raise MXNetError(
                f"{env_var} must be comma-separated ints, got {buckets!r}")
    out = tuple(sorted({int(b) for b in buckets}))
    if not out or out[0] < 1:
        raise MXNetError(f"serving buckets must be positive ints, got {out}")
    return out


class Predictor:
    """A ``(symbol, params)`` checkpoint bound for concurrent inference.

    Parameters
    ----------
    symbol : Symbol
        The inference graph. Loss heads are fine — e.g. ``SoftmaxOutput``
        emits probabilities at inference and its label input is bound to
        zeros (any argument ending in ``label`` that has no value in
        ``arg_params`` is treated this way; other unbound arguments raise,
        catching a checkpoint that is missing a weight).
    arg_params / aux_params : dict[str, NDArray]
        Trained parameters, e.g. from ``model.load_checkpoint``.
    data_shapes : list of (name, shape) or DataDesc
        The data inputs; the leading (batch) dimension is a placeholder —
        actual batch dims come from the bucket ladder.
    buckets : str | iterable of int | None
        Bucket ladder override (default ``MXNET_SERVING_BUCKETS``).
    retry_on : tuple of exception types
        What the batcher treats as a transient executor failure
        (``resilience.retry_call`` semantics; deadline always wins).
    """

    def __init__(self, symbol, arg_params, aux_params=None, data_shapes=None,
                 label_shapes=None, buckets=None, ctx=None,
                 retry_on=(OSError,)):
        from ..context import current_context

        if data_shapes is None:
            raise MXNetError(
                "Predictor needs data_shapes=[(name, shape), ...] — the "
                "batch dim is a placeholder, trailing dims bind the graph")
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else current_context()
        self._data_descs = [d if isinstance(d, DataDesc) else DataDesc(*d)
                            for d in data_shapes]
        self._data_names = [d.name for d in self._data_descs]
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self.retry_on = tuple(retry_on)

        unknown = [n for n in self._data_names if n not in self._arg_names]
        if unknown:
            raise MXNetError(f"data inputs {unknown} are not arguments of "
                             f"the symbol ({self._arg_names})")

        def as_nd(v):
            return v if isinstance(v, nd.NDArray) else nd.array(v)

        arg_params = {k: as_nd(v) for k, v in (arg_params or {}).items()}
        self._arg_params = {n: arg_params[n] for n in self._arg_names
                            if n in arg_params and n not in self._data_names}
        self._aux_params = {k: as_nd(v) for k, v in (aux_params or {}).items()
                            if k in self._aux_names}
        missing_aux = [n for n in self._aux_names if n not in self._aux_params]
        if missing_aux:
            # as loud as a missing weight: zeros here would make e.g.
            # BatchNorm normalize with mean=0/var=0 and serve garbage
            # silently
            raise MXNetError(
                f"auxiliary states {missing_aux} have no value in "
                "aux_params — pass the checkpoint's aux_params (serving "
                "them as zeros would silently corrupt inference, e.g. "
                "BatchNorm moving statistics)")

        # label-style inputs: bound to zeros, shape (bucket,) + trail.
        # Explicit label_shapes wins; otherwise only *label-named* leftovers
        # qualify — any OTHER unbound argument is a missing weight and must
        # fail loudly, not silently serve zeros.
        self._label_trails = {}
        for l in (label_shapes or []):
            d = l if isinstance(l, DataDesc) else DataDesc(*l)
            self._label_trails[d.name] = tuple(d.shape[1:])
        missing = [n for n in self._arg_names
                   if n not in self._data_names
                   and n not in self._arg_params
                   and n not in self._label_trails]
        for n in list(missing):
            if n.endswith("label"):
                self._label_trails[n] = ()
                missing.remove(n)
        if missing:
            raise MXNetError(
                f"arguments {missing} have no value in arg_params and are "
                "not data inputs; pass them in arg_params (weights) or "
                "label_shapes (dummy label inputs)")

        # SPMD serving bind (MXNET_SPMD, parallel/spmd.py): the bound
        # weights are sharded IN PLACE over the one mesh before any
        # bucket executor binds them — every bucket shares the same
        # 1/N-resident buffers, GSPMD propagates the layout through the
        # for_training=False jits. Plan failure logs and stays
        # replicated (the serving twin of Module's _spmd_failed)
        self._spmd_mesh = None
        self._spmd_specs = None
        from ..parallel.spmd import spmd_enabled

        if spmd_enabled():
            from ..log import get_logger
            from ..parallel.spmd import place_serving_params

            try:
                self._spmd_mesh, self._spmd_specs = place_serving_params(
                    symbol, self._arg_params, self._aux_params)
            except Exception as e:  # noqa: BLE001 — bad spec/graph must
                # serve replicated, never fail the bind
                get_logger("mxnet_tpu.serving").warning(
                    "SPMD serving bind unavailable (%r); serving "
                    "replicated weights", e)

        self._buckets = bucket_ladder(buckets)
        self._cache = CompileCache("serving")
        self._execs = {}
        self._lock = analysis.make_rlock("serving.predictor")
        self._weights_version = 0     # bumped by swap_weights (rollout)
        # fleet health: /readyz reports warmup state per predictor
        # (serving.warmup sets _warmed; registration is weakly held)
        self._warmed = False
        from .health import attach_predictor

        self.health_name = attach_predictor(self)
        # memory census: the bound parameters are the serving side's
        # weight residency (SHARED across bucket executors — the census
        # dedupes by buffer, so N buckets still count one copy)
        memory.track("weights", list(self._arg_params.values())
                     + list(self._aux_params.values()))

    # -- construction conveniences ------------------------------------------

    @classmethod
    def load(cls, prefix, epoch=None, data_shapes=None, **kwargs):
        """Bind the newest (or given) ``prefix`` checkpoint for serving —
        ``model.load_checkpoint`` semantics, including corrupt-epoch
        fallback when ``epoch`` is None."""
        from ..model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        if symbol is None:
            raise MXNetError(f"no symbol json found for prefix {prefix!r} "
                             "(need prefix-symbol.json to serve)")
        symbol, arg_params, aux_params = _serving_fused(
            symbol, arg_params, aux_params)
        return cls(symbol, arg_params, aux_params,
                   data_shapes=data_shapes, **kwargs)

    @classmethod
    def from_module(cls, module, buckets=None, **kwargs):
        """Wrap a bound, initialized ``Module``. The Predictor takes COPIES
        of the current parameters (``get_params``), so continuing to train
        the module never mutates a live server."""
        if not (module.binded and module.params_initialized):
            raise MXNetError("from_module needs a bound module with "
                             "initialized parameters")
        arg_params, aux_params = module.get_params()
        kwargs.setdefault("label_shapes", getattr(module, "_label_shapes", None))
        symbol, arg_params, aux_params = _serving_fused(
            module.symbol, arg_params, aux_params)
        return cls(symbol, arg_params, aux_params,
                   data_shapes=module.data_shapes, buckets=buckets, **kwargs)

    # -- properties ----------------------------------------------------------

    @property
    def buckets(self):
        return self._buckets

    @property
    def max_batch(self):
        return self._buckets[-1]

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def output_names(self):
        return list(self._output_names)

    @property
    def cache(self):
        """The shared ``"serving"`` :class:`CompileCache` — ``.misses`` is
        the exact number of programs compiled so far."""
        return self._cache

    def bucket_for(self, rows):
        """Smallest bucket >= ``rows``, or None (caller chunks by
        :attr:`max_batch`)."""
        for b in self._buckets:
            if b >= rows:
                return b
        return None

    # -- binding -------------------------------------------------------------

    def _bind_bucket(self, bucket):
        """The ``for_training=False`` executor of one bucket (bound lazily;
        compile happens on its first forward). Weights/aux are the SHARED
        param NDArrays; its compile cache is re-pointed at the predictor's
        ``"serving"`` cache so all bucket compiles land in one ledger."""
        exec_ = self._execs.get(bucket)
        if exec_ is not None:
            return exec_
        with self._lock:
            exec_ = self._execs.get(bucket)
            if exec_ is not None:
                return exec_
            from ..symbol.executor import Executor

            shape_kwargs = {d.name: (bucket,) + tuple(d.shape[1:])
                            for d in self._data_descs}
            shape_kwargs.update({n: (bucket,) + trail
                                 for n, trail in self._label_trails.items()})
            arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_kwargs)
            dtypes = {d.name: d.dtype for d in self._data_descs}
            args = {}
            for n, s in zip(self._arg_names, arg_shapes):
                p = self._arg_params.get(n)
                if p is not None:
                    if tuple(p.shape) != tuple(s):
                        raise MXNetError(
                            f"parameter {n!r} has shape {tuple(p.shape)} but "
                            f"the graph infers {tuple(s)} — wrong checkpoint "
                            "for this symbol/data_shapes?")
                    args[n] = p
                else:
                    args[n] = nd.zeros(s, dtype=dtypes.get(n, "float32"))
            auxs = {}
            for n, s in zip(self._aux_names, aux_shapes):
                a = self._aux_params[n]
                if tuple(a.shape) != tuple(s):
                    raise MXNetError(
                        f"auxiliary state {n!r} has shape {tuple(a.shape)} "
                        f"but the graph infers {tuple(s)} — wrong "
                        "checkpoint for this symbol/data_shapes?")
                auxs[n] = a
            exec_ = Executor(self._symbol, self._ctx, args=args,
                             grad_req="null", aux_states=auxs)
            exec_._cache = self._cache
            self._execs[bucket] = exec_
            return exec_

    # -- compute -------------------------------------------------------------

    def _run(self, bucket, arrays):
        """Forward ``arrays`` (<= bucket rows, aligned with data_names)
        through the bucket executor; returns the UNSLICED outputs (bucket
        rows). Outputs are materialized before delivery so an execution
        failure surfaces HERE — retryable and attributable — never in a
        caller thread touching a lazy value later."""
        return self._run_wait(self._run_dispatch(bucket, arrays))

    def _run_dispatch(self, bucket, arrays):
        """Dispatch half of :meth:`_run`: pad + forward, NO drain. The
        returned pending handle must be settled with :meth:`_run_wait`;
        between the two the caller owns the host — the batcher's overlap
        lane stages its NEXT flush there while this one executes."""
        exec_ = self._bind_bucket(bucket)
        with tracing.span("serving.pad", cat="serving", bucket=bucket):
            padded, _ = pad_arrays(list(arrays), bucket)
        feed = dict(zip(self._data_names, padded))
        t0 = time.perf_counter() if telemetry._enabled \
            or observatory._enabled else 0.0
        with self._lock, tracing.span("serving.forward", cat="serving",
                                      bucket=bucket):
            outs = list(exec_.forward(is_train=False, **feed))
        return outs, padded, exec_, t0

    def _run_wait(self, pending):
        """Drain a :meth:`_run_dispatch` handle: block on the outputs so
        an execution failure surfaces here (retryable), then account the
        batch. ``exec_s`` spans dispatch->drained — the honest device
        window; the flush WALL is the batcher's to observe, so the
        serving lane's host gap reflects what staging actually hides."""
        import jax

        outs, padded, exec_, t0 = pending
        jax.block_until_ready([o._data for o in outs])
        # in-flight batch residency: weak refs, swept as batches retire
        memory.track_transient("serving_batches", padded + outs)
        tele = telemetry._enabled
        obs = observatory._enabled
        dt = time.perf_counter() - t0 if tele or obs else 0.0
        if tele:
            telemetry.histogram("serving.compute_us").record(dt * 1e6)
        if obs:
            # the executor recorded which compiled entry this forward hit
            observatory.observe("serving", self._cache, exec_._last_fwd_key,
                                exec_s=dt)
        return outs

    # -- weight rollout ------------------------------------------------------

    @property
    def weights_version(self):
        """Version of the currently-bound weight set (0 until the first
        :meth:`swap_weights`)."""
        return self._weights_version

    def swap_weights(self, arg_params, aux_params=None, version=None):
        """Atomic zero-downtime weight flip: substitute new buffers into
        the SHARED param NDArrays every bucket executor binds, under the
        serving lock — an in-flight batch finishes on the old weights
        (``_run`` holds the same lock through its forward), the next
        flush reads the new ones. The incoming arrays are cast to the
        bound dtypes and must match the bound shapes exactly, so every
        warmed ``CompileCache("serving")`` entry is reused untouched:
        the swap compiles NOTHING (executor signatures are shape/dtype
        only, and weights are non-donated arguments).

        ``arg_params`` may be a :class:`~.rollout.WeightSet` (its version
        wins unless ``version`` is passed). Returns the new version, or
        None when ``version`` equals the current one (idempotent
        re-publish). Under an SPMD serving bind the new buffers are
        re-placed with the ORIGINAL sharding specs, so per-device
        residency is preserved across the flip."""
        import jax

        if hasattr(arg_params, "arg_params") and hasattr(arg_params,
                                                         "version"):
            ws = arg_params
            aux_params = ws.aux_params if aux_params is None else aux_params
            version = ws.version if version is None else version
            arg_params = ws.arg_params
        new_arg = dict(arg_params or {})
        new_aux = dict(aux_params or {})
        # mirror _serving_fused's aux->arg migration: a checkpoint
        # published by the training loop still carries e.g. BatchNorm
        # moving stats as aux, but the fused serving graph binds them
        # as plain arguments
        for n in list(new_aux):
            if n in self._arg_params and n not in new_arg:
                new_arg[n] = new_aux.pop(n)
        missing = ([n for n in self._arg_params if n not in new_arg]
                   + [n for n in self._aux_params if n not in new_aux])
        if missing:
            raise MXNetError(
                f"swap_weights: bound parameters {missing} are missing "
                "from the new weight set — a hot swap must cover every "
                "bound array (partial updates would serve a chimera)")
        staged = []
        for tgt_map, src, spmd in ((self._arg_params, new_arg, True),
                                   (self._aux_params, new_aux, False)):
            for n, tgt in tgt_map.items():
                arr = src[n]
                arr = (arr.asnumpy() if hasattr(arr, "asnumpy")
                       else np.asarray(arr))
                if tuple(arr.shape) != tuple(tgt.shape):
                    raise MXNetError(
                        f"swap_weights: parameter {n!r} has shape "
                        f"{tuple(arr.shape)} but the bound executors "
                        f"expect {tuple(tgt.shape)} — identical shapes/"
                        "dtypes are what make the swap compile-free")
                staged.append((n, tgt, arr, spmd))
        with self._lock:
            if version is not None and version == self._weights_version:
                if telemetry._enabled:
                    telemetry.counter("serving.weight_swap_noops").inc()
                return None
            for n, tgt, arr, spmd in staged:
                arr = arr.astype(tgt.dtype, copy=False)
                if self._spmd_mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec

                    spec = (self._spmd_specs.get(n)
                            if spmd and self._spmd_specs else None)
                    data = jax.device_put(
                        arr, NamedSharding(self._spmd_mesh,
                                           spec if spec is not None
                                           else PartitionSpec()))
                else:
                    import jax.numpy as jnp

                    data = jnp.asarray(arr)
                tgt._data = data
            self._weights_version = (self._weights_version + 1
                                     if version is None else int(version))
            swapped_to = self._weights_version
        if telemetry._enabled:
            telemetry.counter("serving.weight_swaps").inc()
            telemetry.gauge("serving.weights_version").set(swapped_to)
        from .. import health

        if health._enabled:
            health.event("rollout_swap", predictor=self.health_name,
                         version=swapped_to)
        return swapped_to

    def warm_bucket(self, bucket):
        """Compile-ahead one bucket: run a zeros batch through it (a cache
        hit if already compiled)."""
        if bucket not in self._buckets:
            raise MXNetError(f"bucket {bucket} not in ladder {self._buckets}")
        zeros = [nd.zeros((bucket,) + tuple(d.shape[1:]), dtype=d.dtype)
                 for d in self._data_descs]
        self._run(bucket, zeros)

    def warmup(self, buckets=None):
        """Compile every bucket ahead of traffic — see
        :func:`mxnet_tpu.serving.warmup`."""
        from .warmup import warmup

        return warmup(self, buckets=buckets)

    def predict(self, data, always_output_list=False):
        """Synchronous single-caller inference: pad ``data`` up to its
        bucket (requests larger than :attr:`max_batch` are chunked), run,
        slice the padding back off. Returns one NDArray when the symbol has
        one output (list otherwise, or always with ``always_output_list``).
        Thread-safe; for concurrent traffic prefer a
        :class:`~mxnet_tpu.serving.batcher.DynamicBatcher`, which coalesces
        callers into shared batches instead of serializing them."""
        arrays = self._as_arrays(data)
        n = int(arrays[0].shape[0])
        parts, off = [], 0
        while off < n:
            take = min(n - off, self.max_batch)
            chunk = [a[off:off + take] for a in arrays]
            outs = self._run(self.bucket_for(take), chunk)
            parts.append([o[0:take] for o in outs])
            off += take
        if len(parts) == 1:
            outs = parts[0]
        else:
            outs = [nd.concatenate([p[i] for p in parts], axis=0)
                    for i in range(len(parts[0]))]
        return self._wrap_outputs(outs, always_output_list)

    # -- helpers -------------------------------------------------------------

    def _as_arrays(self, data):
        """Normalize one request (array, list/tuple aligned with
        data_names, or name->array dict) into a validated NDArray list."""
        if isinstance(data, dict):
            try:
                arrays = [data[n] for n in self._data_names]
            except KeyError as e:
                raise MXNetError(f"request is missing data input {e}")
        elif isinstance(data, (list, tuple)):
            arrays = list(data)
        else:
            arrays = [data]
        if len(arrays) != len(self._data_names):
            raise MXNetError(f"expected {len(self._data_names)} data inputs "
                             f"({self._data_names}), got {len(arrays)}")
        arrays = [a if isinstance(a, nd.NDArray) else nd.array(a)
                  for a in arrays]
        rows = {int(a.shape[0]) for a in arrays}
        if len(rows) != 1:
            raise MXNetError(f"ragged row counts across data inputs: {rows}")
        if rows.pop() == 0:
            raise MXNetError("empty request (0 rows)")
        for a, d in zip(arrays, self._data_descs):
            if tuple(a.shape[1:]) != tuple(d.shape[1:]):
                raise MXNetError(
                    f"input {d.name!r}: trailing shape {tuple(a.shape[1:])} "
                    f"does not match bound {tuple(d.shape[1:])}")
        return arrays

    def _wrap_outputs(self, outs, always_output_list=False):
        if len(outs) == 1 and not always_output_list:
            return outs[0]
        return list(outs)

    def stats(self):
        """{cache snapshot, ladder, bound buckets} — the serving half of
        ``compile_cache.stats()``."""
        return {"cache": self._cache.snapshot(),
                "buckets": list(self._buckets),
                "bound": sorted(self._execs),
                "weights_version": self._weights_version}
