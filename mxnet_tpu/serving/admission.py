"""Admission control: a bounded request queue with backpressure.

A server that queues without bound does not degrade, it collapses — every
request eventually times out after burning queue memory and compute on
work nobody is waiting for. This module is the serving subsystem's intake
valve:

* **bounded depth** — :meth:`AdmissionQueue.put` fast-rejects with
  :class:`QueueFullError` the moment ``MXNET_SERVING_MAX_QUEUE`` requests
  are waiting. The caller learns *immediately* that the server is
  saturated (and can shed load or retry elsewhere) instead of discovering
  it via a timeout later.
* **per-request deadlines** — a request carries an optional absolute
  deadline; the batcher fails expired requests with
  :class:`DeadlineExceededError` *before* spending compute on them, and
  never retries a transient failure past the deadline.
* **graceful drain** — :meth:`close` stops admission
  (:class:`ServerClosedError` for new work) while
  :meth:`get_batch` keeps handing out already-accepted requests until the
  queue is empty, so shutdown completes every promise it admitted.

The flush policy lives here too: :meth:`get_batch` blocks until whichever
comes first of (a) enough queued rows to fill the largest batch bucket, or
(b) the *oldest* queued request having waited ``max_wait`` — timing the
flush from the oldest enqueue means a backlog never waits the full window
again for each successive batch.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import analysis
from .. import health
from .. import telemetry
from ..base import MXNetError, getenv, register_env

__all__ = ["AdmissionQueue", "Request", "ServingError", "QueueFullError",
           "DeadlineExceededError", "ServerClosedError"]

register_env("MXNET_SERVING_MAX_QUEUE", 1024,
             "admission-queue depth bound: serving submit() fast-rejects "
             "with QueueFullError once this many requests are waiting")

# the qos module, bound lazily on first queue construction — qos.py
# imports THIS module for ServingError, so a top-level import would cycle
_qos = None


class ServingError(MXNetError):
    """Base class of serving-plane failures."""


class QueueFullError(ServingError):
    """Backpressure: the admission queue is at ``MXNET_SERVING_MAX_QUEUE``
    requests. Raised synchronously from ``submit()`` — the cheap signal to
    shed load now rather than time out later."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before a result could be computed
    (in queue, or between transient-failure retries — a retry is never
    attempted past the deadline)."""


class ServerClosedError(ServingError):
    """``submit()`` after ``close()``: the server is draining/stopped."""


class Request:
    """One admitted inference request: input arrays plus delivery future.

    ``deadline`` is an absolute ``time.monotonic()`` instant (or None);
    ``enqueued_at`` is stamped at construction and drives both the flush
    timer and the ``serving.time_in_queue_us`` histogram.

    A request may be SPLIT at a batch boundary (``AdmissionQueue``
    ``_split``) so that every max-batch flush is exactly full: the popped
    head piece points back at the original via ``parent``/``offset`` and
    the original is mutated down to its tail rows in place (keeping its
    queue position, future and enqueue time). The batcher reassembles the
    pieces by offset before resolving the future.
    """

    __slots__ = ("arrays", "rows", "future", "deadline", "enqueued_at",
                 "parent", "offset", "total_rows", "parts", "span",
                 "traced_queue", "flow_ended", "payload", "tenant",
                 "qos_rank", "qos_exempt")

    def __init__(self, arrays, rows, future, deadline=None, payload=None,
                 tenant=None):
        self.arrays = arrays
        self.rows = int(rows)
        self.future = future
        self.deadline = deadline
        self.payload = payload      # owner-defined (a generation session)
        self.tenant = tenant        # QoS tenant name (None = default class)
        self.qos_rank = None        # class rank stamped at put() (QoS on)
        self.qos_exempt = False     # skip quotas: re-admission of already-
        #                             admitted work (preemption migration)
        self.enqueued_at = time.monotonic()
        self.parent = None          # set on split-off head pieces
        self.offset = 0             # row offset within the original request
        self.total_rows = self.rows  # original size (pieces keep parent's)
        self.parts = None           # on the original: delivered pieces
        self.span = None            # tracing root span (MXNET_TRACING=1)
        self.traced_queue = False   # queue span emitted for THIS piece (a
        #                             deadline-survivor re-run must not
        #                             emit it a second time)
        self.flow_ended = False     # flow arrow landed (checked/set on the
        #                             ORIGIN: one arrow per request, however
        #                             many pieces or re-runs it takes)

    @property
    def origin(self):
        """The request whose future this piece resolves (itself, unless
        split off)."""
        return self.parent if self.parent is not None else self


class AdmissionQueue:
    """Bounded FIFO of :class:`Request` with the batch-flush wait logic.

    ``metric_prefix`` names the telemetry series this queue publishes
    (``<prefix>.queue_depth`` gauge, ``<prefix>.rejected`` counter) — the
    batcher keeps the historical ``serving.*`` names, the generation
    engine's intake reports as ``serving.generation.*``.

    With a QoS registry active (:mod:`.qos`, captured at construction)
    the pop order becomes ``(class rank, earliest deadline, enqueue
    time)`` — batch requests age into standard rank per
    ``MXNET_QOS_AGING_S`` — and ``put()`` additionally enforces
    per-tenant quotas (:class:`~.qos.QuotaExceededError`). Without one,
    every path below is byte-identical to the pre-QoS FIFO (pinned by
    ``test_qos.py``)."""

    def __init__(self, max_depth=None, metric_prefix="serving"):
        self._max_depth = int(getenv("MXNET_SERVING_MAX_QUEUE")
                              if max_depth is None else max_depth)
        self._prefix = metric_prefix
        if self._max_depth < 1:
            raise MXNetError("serving queue depth must be >= 1, got "
                             f"{self._max_depth}")
        global _qos
        if _qos is None:
            from . import qos as _qos_module

            _qos = _qos_module
        self._qos = _qos.active()
        self._q = collections.deque()
        self._rows = 0
        self._cond = analysis.make_condition(f"{metric_prefix}.admission")
        self._closed = False
        # set (by the batcher, under its assist lock) while a blocking
        # caller is draining inline: put() then skips the worker wakeup —
        # the assistant will pop the request anyway, and a woken worker
        # would only convoy with it on the GIL. The assistant kick()s the
        # worker for anything it leaves behind.
        self.assist_active = False

    def __len__(self):
        with self._cond:
            return len(self._q)

    @property
    def closed(self):
        return self._closed

    @property
    def max_depth(self):
        return self._max_depth

    def put(self, req):
        """Admit ``req`` or reject NOW (QueueFullError / ServerClosedError
        / — QoS active — QuotaExceededError for an over-quota tenant).
        Never blocks — backpressure is a synchronous signal, not a stall."""
        with self._cond:
            if self._closed:
                raise ServerClosedError(
                    "serving queue is closed; no new requests accepted")
            spec = None
            if self._qos is not None:
                spec = self._qos.spec_for(req.tenant)
                req.qos_rank = spec.rank
                if not req.qos_exempt:
                    try:
                        self._qos.check_admit(req.tenant)
                    except Exception as e:
                        if telemetry._enabled:
                            telemetry.counter(
                                f"{self._prefix}.rejected").inc()
                            telemetry.counter(_qos.labeled_metric(
                                "qos.rejected", spec)).inc()
                        if health._enabled:
                            health.event("qos_quota_reject",
                                         prefix=self._prefix,
                                         tenant=spec.name, cls=spec.cls,
                                         error=repr(e))
                        raise
            if len(self._q) >= self._max_depth:
                if telemetry._enabled:
                    telemetry.counter(f"{self._prefix}.rejected").inc()
                    if spec is not None:
                        telemetry.counter(_qos.labeled_metric(
                            "qos.rejected", spec)).inc()
                if health._enabled:
                    health.event("admission_reject", prefix=self._prefix,
                                 depth=len(self._q))
                raise QueueFullError(
                    f"serving queue full ({len(self._q)} >= "
                    f"{self._max_depth} requests); shed load or raise "
                    "MXNET_SERVING_MAX_QUEUE")
            self._q.append(req)
            self._rows += req.rows
            if telemetry._enabled:
                telemetry.gauge(f"{self._prefix}.queue_depth").set(
                    len(self._q))
                if spec is not None:
                    telemetry.counter(_qos.labeled_metric(
                        "qos.admitted", spec)).inc()
                    self._qos_depth_gauges()
            if not self.assist_active:
                self._cond.notify()

    def kick(self):
        """Wake the worker (an exiting assistant calls this so requests it
        left queued are not stranded behind a swallowed notify)."""
        with self._cond:
            self._cond.notify_all()

    def _qos_sort(self, now=None):
        """Reorder the queue by (effective class rank, earliest deadline,
        enqueue time) — called under the held condition right before a
        pop, because batch->standard aging makes the effective rank a
        function of NOW. Stable within a key, so equal-priority requests
        stay FIFO. No-op while QoS is off."""
        if self._qos is None or len(self._q) < 2:
            return
        now = time.monotonic() if now is None else now
        reg, inf = self._qos, float("inf")
        self._q = collections.deque(sorted(
            self._q,
            key=lambda r: (reg.effective_rank(r.qos_rank, r.enqueued_at,
                                              now),
                           r.deadline if r.deadline is not None else inf,
                           r.enqueued_at)))

    def _qos_depth_gauges(self):
        """Per-class queue-depth gauges (held condition; QoS + telemetry
        on). O(queue) — admission pops are already O(queue log queue)."""
        counts = {cls: 0 for cls in _qos.CLASSES}
        for r in self._q:
            rank = (self._qos.default_rank if r.qos_rank is None
                    else r.qos_rank)
            counts[_qos.CLASSES[rank]] += 1
        for cls, n in counts.items():
            telemetry.gauge(telemetry.labeled(
                "qos.queue_depth", **{"class": cls})).set(n)

    def peek(self):
        """The request the next pop would hand out (QoS order when
        active), skipping already-resolved futures — the generation
        engine's preemption probe. None when nothing is pending."""
        with self._cond:
            self._qos_sort()
            for r in self._q:
                if not r.origin.future.done():
                    return r
            return None

    def weighted_depth(self):
        """Fairness-weighted queue depth (QoS registry weights; plain
        ``len`` while QoS is off) — the autoscale demand contribution."""
        with self._cond:
            if self._qos is None:
                return float(len(self._q))
            return float(sum(self._qos.weight(r.tenant) for r in self._q))

    def get_batch(self, max_rows, max_wait_s):
        """Block until a flushable batch is ready and pop it.

        Returns ``(requests, reason)`` with ``reason`` one of ``"full"``
        (queued rows reached ``max_rows``), ``"timeout"`` (the oldest
        request waited ``max_wait_s``) or ``"drain"`` (queue closed,
        handing out the remainder) — or ``(None, None)`` once closed AND
        empty, the worker's exit signal.

        The pop is FIFO in row order: whole requests while they fit, and
        the boundary request is SPLIT so a ``"full"`` flush carries
        exactly ``max_rows`` rows (the tail piece keeps the head of the
        queue, its future and its enqueue time). Oversize requests
        (rows > max_rows) are consumed the same way, max_rows per batch.
        Pieces whose future already resolved (an earlier piece failed)
        are dropped unrun."""
        with self._cond:
            while True:
                while not self._q and not self._closed:
                    self._cond.wait()
                if not self._q:
                    return None, None  # closed and drained
                if self._closed:
                    reason = "drain"
                elif self._rows >= max_rows:
                    reason = "full"
                else:
                    oldest = self._q[0].enqueued_at
                    if self._qos is not None:
                        # priority reordering can bury the oldest request
                        # behind the head — the flush timer must still
                        # honor ITS age or a backlogged batch request
                        # waits the full window once per pop
                        oldest = min(r.enqueued_at for r in self._q)
                    remaining = oldest + max_wait_s - time.monotonic()
                    if remaining > 0:
                        self._cond.wait(timeout=remaining)
                        continue
                    reason = "timeout"
                out = self._pop(max_rows)
                if out:
                    return out, reason
                # everything queued was already-failed pieces: wait again

    def get_batch_nowait(self, max_rows):
        """Non-blocking pop for an ASSISTING caller (a blocking
        ``predict()`` that found the batcher idle runs batches inline
        instead of paying two thread handoffs): whatever is queued right
        now — reason ``"inline"`` (``"drain"`` once closed) — or
        ``(None, None)`` when the queue is empty."""
        with self._cond:
            out = self._pop(max_rows)
            if not out:
                return None, None
            return out, ("drain" if self._closed else "inline")

    def _pop(self, max_rows):
        """FIFO row-order pop under the held condition: whole requests
        while they fit, the boundary request split at ``max_rows``.
        With QoS active the 'FIFO' order is the class/deadline order
        :meth:`_qos_sort` just imposed."""
        self._qos_sort()
        out, rows = [], 0
        while self._q and rows < max_rows:
            req = self._q[0]
            if req.origin.future.done():
                # an earlier piece already failed this request — don't
                # burn compute on the rest of it
                self._q.popleft()
                self._rows -= req.rows
                continue
            if rows + req.rows <= max_rows:
                self._q.popleft()
                self._rows -= req.rows
                rows += req.rows
                out.append(req)
            else:
                k = max_rows - rows
                out.append(self._split(req, k))
                self._rows -= k
                rows += k
        if telemetry._enabled:
            telemetry.gauge(f"{self._prefix}.queue_depth").set(len(self._q))
            if self._qos is not None:
                self._qos_depth_gauges()
        return out

    def expire(self, now=None):
        """Remove and return every queued request whose deadline has
        passed (skipping already-resolved futures). The generation
        engine sweeps this once per scheduler tick so a stream that will
        never fit a slot in time fails with
        :class:`DeadlineExceededError` NOW instead of wedging its
        iterator until a slot frees up; the caller fails the returned
        requests' futures/streams itself."""
        now = time.monotonic() if now is None else now
        with self._cond:
            expired = [r for r in self._q
                       if r.deadline is not None and now >= r.deadline
                       and not r.origin.future.done()]
            for r in expired:
                self._q.remove(r)
                self._rows -= r.rows
            if expired and telemetry._enabled:
                telemetry.gauge(f"{self._prefix}.queue_depth").set(
                    len(self._q))
                if self._qos is not None:
                    self._qos_depth_gauges()
        return expired

    @staticmethod
    def _split(req, k):
        """Carve the first ``k`` rows of ``req`` into a piece pointing back
        at the original; ``req`` keeps the tail in place (same future,
        deadline and enqueue time — the flush timer still sees the
        original age)."""
        head = Request([a[0:k] for a in req.arrays], k, req.future,
                       deadline=req.deadline, tenant=req.tenant)
        head.qos_rank = req.qos_rank
        head.enqueued_at = req.enqueued_at
        head.parent = req.origin
        head.offset = req.offset
        head.total_rows = req.total_rows
        req.arrays = [a[k:] for a in req.arrays]
        req.rows -= k
        req.offset += k
        return head

    def close(self):
        """Stop admitting; wake every waiter so the worker can drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
