"""mxnet_tpu.serving — the inference serving subsystem.

Everything before this package is training-side; this is the first layer
driven by *concurrent callers* instead of a training loop. It turns a
trained ``(symbol, params)`` checkpoint (or a bound ``Module``) into a
thread-safe server:

* :class:`Predictor` — ``for_training=False`` executors bound per
  batch-size bucket (``MXNET_SERVING_BUCKETS``), requests padded up to
  the smallest fitting bucket via ``io.pad_arrays``, every compile in ONE
  named ``CompileCache("serving")``;
* :class:`DynamicBatcher` — queues individual requests and coalesces them
  into padded batches, flushing on max-batch or
  ``MXNET_SERVING_MAX_WAIT_MS``, each caller getting exactly its own rows
  back;
* admission control — ``MXNET_SERVING_MAX_QUEUE`` bounds the queue
  (synchronous :class:`QueueFullError` backpressure), per-request
  deadlines (:class:`DeadlineExceededError`), graceful ``close()`` drain
  (:class:`ServerClosedError` for new work), transient executor failures
  retried with ``resilience.retry_call`` semantics but never past a
  deadline;
* :func:`warmup` — compile-ahead of every bucket so steady-state traffic
  never pays a compile (exact count pinned by test); also warms
  generation engines (prefill ladder + decode);
* :mod:`generation` — continuous-batching autoregressive serving: a
  slot-based KV-cache session store with a token-level scheduler
  (:class:`GenerationEngine`), streaming sessions
  (:class:`GenerationStream`) and an occupancy-aware replica router
  (:class:`GenerationRouter`);
* telemetry — ``serving.*`` metrics: queue-depth gauge, batch-occupancy
  histogram, time-in-queue / compute / end-to-end latency p50-p95-p99,
  timeout + rejected counters, and the derived
  ``serving.batch_fill_ratio`` (``tools/telemetry_report.py`` renders a
  summary; ``docs/faq/perf.md`` explains how to size buckets from it);
* :mod:`qos` — multi-tenant quality of service (``MXNET_QOS_SPEC``):
  priority-classed (interactive/standard/batch) deadline-aware admission
  ordering with per-tenant rate quotas (:class:`QuotaExceededError`),
  anti-starvation aging, preemptive parking of batch sessions into the
  KV slab's park region under interactive pressure (bit-exact resume via
  the traced fork executable), and per-tenant/per-class ``qos.*``
  telemetry + SLO burn rows;
* :mod:`rollout` — zero-downtime train→serve weight streaming: versioned
  CRC-verified :class:`WeightSet` publishes over a watched directory
  (``MXNET_ROLLOUT_DIR``), atomic ``swap_weights`` hot-flips on both
  serving stacks with zero steady-state compiles, and SLO-burn-gated
  ``GenerationRouter.rolling_swap`` with automatic journaled rollback.

Quick start::

    pred = serving.Predictor.load("model", data_shapes=[("data", (1, 3, 224, 224))])
    serving.warmup(pred)                     # compile every bucket now
    with serving.DynamicBatcher(pred) as srv:
        fut = srv.submit(batch_of_rows, timeout=0.5)
        probs = fut.result()
"""
from .admission import (AdmissionQueue, DeadlineExceededError, QueueFullError,
                        Request, ServerClosedError, ServingError)
from .batcher import DynamicBatcher
from .generation import GenerationEngine, GenerationRouter, GenerationStream
from .predictor import Predictor, bucket_ladder
from .qos import QuotaExceededError, TenantRegistry
from .rollout import (RolloutSubscriber, RolloutWatcher, WeightSet, publish,
                      publish_checkpoint)
from .warmup import warmup
from . import generation
from . import qos
from . import rollout

__all__ = ["Predictor", "DynamicBatcher", "AdmissionQueue", "Request",
           "ServingError", "QueueFullError", "DeadlineExceededError",
           "ServerClosedError", "QuotaExceededError", "bucket_ladder",
           "warmup", "generation", "GenerationEngine", "GenerationRouter",
           "GenerationStream", "qos", "TenantRegistry", "rollout",
           "WeightSet", "RolloutSubscriber", "RolloutWatcher", "publish",
           "publish_checkpoint"]
