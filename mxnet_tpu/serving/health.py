"""Serving-side health probes: per-object liveness/readiness wiring.

The core health layer (:mod:`mxnet_tpu.health`) owns the registries, the
watchdog and the SLO tracker; this module is the glue that teaches the
serving objects to report into them:

* :func:`attach_engine` — a :class:`GenerationEngine` registers a
  liveness probe (scheduler worker thread alive), a readiness probe
  (warmed + intake queue below the watermark + tick beacon not stalled +
  not draining) and a progress beacon the stall watchdog monitors (armed
  on submit, touched per scheduler tick, idled when the slab empties).
* :func:`attach_batcher` / :func:`attach_predictor` — the request-level
  serving plane: worker-thread liveness, queue-watermark + warmed
  readiness.

Readiness drives PLACEMENT, not existence: the
:class:`~mxnet_tpu.serving.generation.router.GenerationRouter` skips
engines whose readiness probe fails (drain — live sessions finish, new
sessions go elsewhere) and re-admits them the moment the probe passes
again. ``/readyz`` aggregates the same probes per process.

Everything here is construction-time registration (weak references, a
few dict entries); the hot paths pay the usual one
``health._enabled`` attribute read when the layer is off.
"""
from __future__ import annotations

import itertools

from .. import health
from ..base import getenv

__all__ = ["attach_engine", "attach_batcher", "attach_predictor",
           "queue_watermark", "queue_ready"]

_seq = itertools.count()


def queue_watermark():
    """The readiness watermark fraction (``MXNET_HEALTH_QUEUE_WATERMARK``
    of the admission bound)."""
    return float(getenv("MXNET_HEALTH_QUEUE_WATERMARK"))


def queue_ready(queue):
    """(ok, detail) for one admission queue against the watermark."""
    depth = len(queue)
    limit = queue.max_depth * queue_watermark()
    if depth >= limit:
        return False, (f"queue depth {depth} >= watermark "
                       f"{limit:.0f} (of {queue.max_depth})")
    return True, f"queue {depth}/{queue.max_depth}"


def _engine_live(e):
    return e.healthy()


def _engine_ready(e):
    return e.ready()


def attach_engine(engine):
    """Register one generation engine's probes + tick beacon. Returns the
    (engine-unique) probe name, which is also the beacon name."""
    name = f"generation.engine.{next(_seq)}"
    health.register_liveness(name, engine, _engine_live)
    health.register_readiness(name, engine, _engine_ready)
    return name, health.beacon(name, owner=engine)


def _batcher_live(b):
    return b.healthy()


def _batcher_ready(b):
    return b.ready()


def attach_batcher(batcher):
    name = f"serving.batcher.{next(_seq)}"
    health.register_liveness(name, batcher, _batcher_live)
    health.register_readiness(name, batcher, _batcher_ready)
    return name


def _predictor_ready(p):
    # traffic-compiled predictors count as warmed (the engine rule): a
    # deployment that skipped serving.warmup() but has bound buckets is
    # serving fine and must not report 503 forever
    if not p._warmed and not p._execs:
        return False, "warmup not run"
    return True, f"buckets bound: {sorted(p._execs)}"


def attach_predictor(predictor):
    name = f"serving.predictor.{next(_seq)}"
    health.register_readiness(name, predictor, _predictor_ready)
    return name
