"""Compile-ahead warmup: pay every bucket's compile before traffic exists.

A serving process that compiles lazily pays its XLA compile on the first
unlucky *user* request of each bucket shape — seconds of p99 latency that
look like an outage. Warmup runs a zeros batch through every bucket at
startup, so the ``"serving"`` compile cache is fully populated before the
first real request and steady state pays ZERO compiles (pinned by
test_serving.py the way the fused-step PR pinned its padded-batch miss
count). With ``MXNET_COMPILE_CACHE_DIR`` set, later processes deserialize
these programs instead of rebuilding them — warmup then costs disk reads,
not compiles.
"""
from __future__ import annotations

import time

from .. import telemetry
from ..log import get_logger

__all__ = ["warmup"]


def warmup(target, buckets=None):
    """Compile every executable of ``target`` ahead of traffic.

    ``target`` is a ``Predictor`` or ``DynamicBatcher`` (one forward
    program per batch bucket), or a ``GenerationEngine`` /
    ``GenerationRouter`` (one prefill program per prompt-length bucket
    plus THE decode program, per replica).

    Returns ``{"buckets", "compiles", "seconds", "cache_entries"}`` —
    ``compiles`` is the exact number of new programs built (cache-miss
    delta), so a second call reports 0. ``serving.warmup_compiles`` /
    ``serving.generation.warmup_compiles`` ride the telemetry registry
    when enabled.
    """
    if hasattr(target, "prefill_buckets") or (
            hasattr(target, "engines")
            and any(hasattr(e, "prefill_buckets")
                    for e in getattr(target, "engines", []))):
        # generation plane: the engine/router owns the exact-count warm
        # (prefill ladder + decode, free-slot safe) — see
        # GenerationEngine.warm
        return target.warm(buckets)
    pred = getattr(target, "predictor", target)
    buckets = (pred.buckets if buckets is None
               else tuple(sorted({int(b) for b in buckets})))
    cache = pred.cache
    misses0 = cache.misses
    t0 = time.perf_counter()
    for b in buckets:
        pred.warm_bucket(b)
    compiles = cache.misses - misses0
    seconds = time.perf_counter() - t0
    pred._warmed = True           # readiness: warmup complete (/readyz)
    if telemetry._enabled:
        telemetry.counter("serving.warmup_compiles").inc(compiles)
    get_logger("mxnet_tpu.serving").info(
        "serving warmup: %d bucket(s) -> %d compile(s) in %.2fs "
        "(cache %r now holds %d executables)",
        len(buckets), compiles, seconds, cache.name, len(cache))
    return {"buckets": list(buckets), "compiles": compiles,
            "seconds": seconds, "cache_entries": len(cache)}
