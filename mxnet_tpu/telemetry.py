"""Runtime telemetry: a process-wide metrics registry + export paths.

PR 1 (resilience) made failures survivable; this layer makes the runtime
*measurable*. The reference ships per-op timing through the profiler
(`src/profiler/`) but has no cross-layer metrics plane — a slow multi-host
run is diagnosed by eyeballing logs. Here every hot path the framework owns
reports into one registry:

* ``engine.*``   — push→run latency, queue depth, async errors
  (:mod:`mxnet_tpu.engine`);
* ``io.*``       — prefetch wait vs. compute time and the derived
  starvation ratio (:class:`mxnet_tpu.io.PrefetchingIter`), plus the
  transient-IO retry counters fed from :func:`mxnet_tpu.resilience.retry_call`;
* ``kvstore.*`` / ``dist.*`` — push/pull bytes + latency, collective bytes,
  barrier straggler wait (:mod:`mxnet_tpu.kvstore`,
  :mod:`mxnet_tpu.parallel.dist`);
* ``checkpoint.*`` — save/load duration, bytes, CRC-fallback events
  (:mod:`mxnet_tpu.model`, :mod:`mxnet_tpu.ndarray.utils`);
* ``step.*``     — per-training-step breakdown (data / forward-backward /
  update / sync) recorded by ``BaseModule.fit`` and surfaced through
  ``BatchEndParam.step_stats`` so ``Speedometer`` logs p50/p99 step latency
  alongside samples/sec; the ``step.fused`` gauge is 1 while training runs
  the fused single-XLA-computation path and 0 on the eager fallback;
* ``compile.*`` — the :mod:`mxnet_tpu.compile_cache` plane:
  ``compile.cache_hits`` / ``compile.cache_misses`` counters,
  ``compile.seconds`` (cumulative first-call/compile time),
  ``compile.cache_entries`` gauge (live executables across all caches) and
  the derived ``compile.cache_hit_ratio``. Unlike the rest of the registry
  these are recorded unconditionally — recompile churn must be visible
  even when the wider telemetry plane is off.

Metric kinds: :class:`Counter` (monotonic), :class:`Gauge` (set/inc/dec),
:class:`Histogram` (exact count/sum/min/max + a bounded reservoir for
p50/p95/p99 — memory is O(reservoir), never O(samples)).

Export, three ways:

1. :func:`dumps` — JSON snapshot; ``MXNET_TELEMETRY_DUMP=<path>`` writes it
   at interpreter exit via the same temp-file + fsync + atomic-rename path
   checkpoints use, so a crash mid-dump can never leave a torn snapshot.
2. :func:`trace_counter_events` — chrome-trace ``"C"`` (counter) events
   merged into ``profiler.dump()`` output, so metrics line up with the XLA
   trace timeline in chrome://tracing / perfetto.
3. periodic log summaries through :func:`mxnet_tpu.log.get_logger`
   (``MXNET_TELEMETRY_LOG_INTERVAL_S``).

Overhead discipline: everything is gated on the module-level ``_enabled``
flag (``MXNET_TELEMETRY=1`` or :func:`enable`). Instrumented call sites
check the flag BEFORE taking any timestamp, so a disabled registry costs
one attribute read per call — nothing else.
"""
from __future__ import annotations

import atexit
import json
import os
import random
import sys
import threading
import time

from .base import getenv, register_env
from .log import get_logger

__all__ = ["Counter", "Gauge", "Histogram",
           "counter", "gauge", "histogram", "get", "labeled",
           "enabled", "enable", "disable", "reset",
           "snapshot", "dumps", "dump", "dumps_table", "prom_text",
           "trace_counter_events", "start_log_thread", "stop_log_thread",
           "start_http_server", "stop_http_server"]

register_env("MXNET_TELEMETRY", False, "enable the runtime metrics registry")
register_env("MXNET_TELEMETRY_DUMP", "",
             "write a telemetry.dumps() JSON snapshot to this path at exit")
register_env("MXNET_TELEMETRY_LOG_INTERVAL_S", 0.0,
             "log a telemetry summary every N seconds (0 = off)")
register_env("MXNET_TELEMETRY_RESERVOIR", 1024,
             "histogram reservoir size (quantile accuracy vs. memory)")
register_env("MXNET_TELEMETRY_HTTP_PORT", 0,
             "serve /metrics (Prometheus text), /trace (chrome trace + "
             "worst-step/tick span trees), /memory (device-buffer census) "
             "and the health plane (/slo, /healthz, /readyz, /events) on "
             "this port from a background thread (0 = off)")
register_env("MXNET_TELEMETRY_HTTP_HOST", "127.0.0.1",
             "bind address for the telemetry HTTP endpoint — loopback by "
             "default; traces carry request args and file paths, so expose "
             "on other interfaces (e.g. 0.0.0.0 for a Prometheus scrape "
             "from another host) deliberately")

# THE gate. Call sites read `telemetry._enabled` (one attribute fetch)
# before doing any telemetry work, including taking timestamps.
_enabled = bool(getenv("MXNET_TELEMETRY"))

_registry = {}            # name -> metric
_registry_lock = threading.Lock()


def _logger():
    from . import log as _log

    return get_logger("mxnet_tpu.telemetry", level=_log.INFO)


# ---------------------------------------------------------------------------
# Metric kinds
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic counter (events, bytes, retries)."""

    kind = "counter"
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Point-in-time value (queue depth, ratios)."""

    kind = "gauge"
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


def _percentile(samples, q):
    """q-th percentile (0-100) of an already-sorted sample list; None when
    empty. THE quantile formula — every export path uses this one."""
    if not samples:
        return None
    last = len(samples) - 1
    return samples[max(0, min(int(round(q / 100.0 * last)), last))]


class Histogram:
    """Latency/size distribution: exact count/sum/min/max plus a bounded
    reservoir (Vitter's algorithm R) for p50/p95/p99 — a week-long run
    records billions of steps in O(reservoir) memory."""

    kind = "histogram"
    __slots__ = ("name", "_lock", "_count", "_sum", "_min", "_max",
                 "_reservoir", "_cap")

    def __init__(self, name, reservoir=None):
        self.name = name
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._cap = int(reservoir if reservoir is not None
                        else getenv("MXNET_TELEMETRY_RESERVOIR"))
        self._reservoir = []

    def record(self, v):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                j = random.randrange(self._count)
                if j < self._cap:
                    self._reservoir[j] = v

    @property
    def count(self):
        return self._count

    def percentile(self, q):
        """Approximate q-th percentile (0-100) from the reservoir."""
        return self.quantiles(q)[0]

    def quantiles(self, *qs):
        """Several percentiles from ONE sorted reservoir copy (the hot-loop
        spelling: p50+p99 per step must not sort twice). None entries when
        the reservoir is empty (no samples yet, or reservoir size 0)."""
        with self._lock:
            samples = sorted(self._reservoir)
        return tuple(_percentile(samples, q) for q in qs)

    def snapshot(self):
        with self._lock:
            count, total = self._count, self._sum
            samples = sorted(self._reservoir)
        if not count:
            return {"count": 0, "sum": 0.0, "min": None, "max": None,
                    "avg": None, "p50": None, "p95": None, "p99": None}
        return {"count": count, "sum": total,
                "min": self._min, "max": self._max, "avg": total / count,
                "p50": _percentile(samples, 50),
                "p95": _percentile(samples, 95),
                "p99": _percentile(samples, 99)}


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def _get_or_create(name, cls):
    m = _registry.get(name)
    if m is not None:
        if not isinstance(m, cls):
            raise TypeError(
                f"telemetry metric {name!r} already registered as {m.kind}")
        return m
    with _registry_lock:
        m = _registry.get(name)
        if m is None:
            m = _registry[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"telemetry metric {name!r} already registered as {m.kind}")
    return m


def counter(name):
    """Get-or-create the :class:`Counter` named ``name``."""
    return _get_or_create(name, Counter)


def gauge(name):
    """Get-or-create the :class:`Gauge` named ``name``."""
    return _get_or_create(name, Gauge)


def histogram(name):
    """Get-or-create the :class:`Histogram` named ``name``."""
    return _get_or_create(name, Histogram)


def get(name):
    """The metric named ``name``, or None."""
    return _registry.get(name)


def labeled(name, **labels):
    """Compose a metric name carrying Prometheus-style labels:
    ``labeled("qos.admitted", tenant="acme")`` ->
    ``"qos.admitted|tenant=acme"`` (keys sorted for a stable identity).
    Flat views (``dumps_table``, ``snapshot``) show the composed name;
    :func:`prom_text` splits it back into a real label set —
    ``mxnet_qos_admitted{tenant="acme"}`` — so per-tenant series land as
    one metric family, not N name-mangled metrics. Label VALUES have the
    ``|``/``=`` separators sanitized to ``_``; the label-escape path
    handles the rest at render time."""
    parts = [name]
    for k in sorted(labels):
        v = str(labels[k]).replace("|", "_").replace("=", "_")
        parts.append(f"{k}={v}")
    return "|".join(parts)


def enabled():
    return _enabled


def enable(on=True):
    """Turn the registry on (also: ``MXNET_TELEMETRY=1`` at import)."""
    global _enabled
    _enabled = bool(on)
    if _enabled:
        start_log_thread()


def disable():
    enable(False)


def reset():
    """Drop every metric (tests; a fresh registry, enabled state kept)."""
    with _registry_lock:
        _registry.clear()


# ---------------------------------------------------------------------------
# Snapshot / export
# ---------------------------------------------------------------------------


def snapshot():
    """One coherent dict of every metric: {counters, gauges, histograms,
    derived}. ``derived`` carries cross-metric ratios, e.g. the prefetch
    starvation ratio wait/(wait+compute) — >0.5 means the step loop spends
    more time waiting on data than computing (docs/faq/perf.md)."""
    with _registry_lock:
        metrics = list(_registry.values())
    out = {"ts": time.time(), "pid": os.getpid(),
           "counters": {}, "gauges": {}, "histograms": {}, "derived": {}}
    for m in metrics:
        out[m.kind + "s"][m.name] = m.snapshot()
    wait = out["counters"].get("io.prefetch_wait_us_total", 0.0)
    compute = out["counters"].get("io.prefetch_compute_us_total", 0.0)
    if wait + compute > 0:
        out["derived"]["io.starvation_ratio"] = wait / (wait + compute)
    swait = out["counters"].get("io.stage_wait_us_total", 0.0)
    sprep = out["counters"].get("io.stage_prep_us_total", 0.0)
    if swait + sprep > 0:
        # time the consumer blocked on the staging thread over total
        # staging time — near 0 means batches are fully prepared behind
        # device compute, near 1 means staging isn't hiding anything
        # (docs/faq/perf.md "Closing the host gap")
        out["derived"]["io.stage_wait_ratio"] = swait / (swait + sprep)
    step_wall = out["counters"].get("step.wall_us_total", 0.0)
    if step_wall > 0:
        # every host-side input stall a step can see — prefetch wait plus
        # stage wait — over step wall time: the one number that says how
        # much of the run the input pipeline cost (composes PrefetchingIter
        # starvation with DeviceStager waits)
        out["derived"]["io.pipeline_stall_ratio"] = min(
            (wait + swait) / step_wall, 1.0)
    hits = out["counters"].get("compile.cache_hits", 0)
    misses = out["counters"].get("compile.cache_misses", 0)
    if hits + misses > 0:
        # low ratio at steady state = recompile churn (docs/faq/perf.md
        # "Reading compile-cache telemetry")
        out["derived"]["compile.cache_hit_ratio"] = hits / (hits + misses)
    rows = out["counters"].get("serving.batch_rows", 0)
    slots = out["counters"].get("serving.batch_slots", 0)
    if slots > 0:
        # real rows per padded batch slot — low fill means the bucket
        # ladder or flush window is wasting compute on padding
        # (docs/faq/perf.md "Sizing serving buckets")
        out["derived"]["serving.batch_fill_ratio"] = rows / slots
    dtok = out["counters"].get("serving.generation.decode_tokens", 0)
    cap = out["counters"].get("serving.generation.tick_slots", 0)
    if cap > 0:
        # live sessions per slab slot per decode tick — low fill means the
        # KV slab is oversized for the arrival rate (padding compute on
        # dead slots; docs/faq/perf.md "Sizing the KV slab")
        out["derived"]["serving.generation.slot_fill_ratio"] = dtok / cap
    prop = out["counters"].get("serving.generation.spec.proposed", 0)
    if prop > 0:
        # draft quality: accepted proposals over proposed — the lever
        # behind tokens-per-tick (docs/faq/perf.md "Prefix caching and
        # speculative decoding")
        out["derived"]["serving.generation.spec.acceptance_ratio"] = \
            out["counters"].get("serving.generation.spec.accepted", 0) / prop
    vslots = out["counters"].get("serving.generation.spec.verified_slots", 0)
    if vslots > 0:
        # committed tokens per live slot per verify tick: 1.0 is the
        # plain-decode floor, spec_k+1 the ceiling
        out["derived"]["serving.generation.spec.accepted_tokens_per_tick"] = \
            out["counters"].get("serving.generation.spec.committed", 0) \
            / vslots
    ph = out["counters"].get("serving.generation.prefix.hits", 0)
    pm = out["counters"].get("serving.generation.prefix.misses", 0)
    if ph + pm > 0:
        # admissions served by a fork instead of a full prefill — a
        # fleet sharing a system prompt should approach (N-1)/N
        out["derived"]["serving.generation.prefix.hit_ratio"] = \
            ph / (ph + pm)
    segs = out["counters"].get("lazy.segments", 0)
    if segs > 0:
        # fused ops per flushed lazy segment — near 1 means barriers fire
        # per op and capture buys nothing (docs/faq/perf.md "Reading
        # lazy-segment telemetry")
        out["derived"]["lazy.mean_ops_per_segment"] = \
            out["counters"].get("lazy.ops_captured", 0) / segs
    rseg = out["counters"].get("lazy.rewrite.segments", 0)
    if rseg > 0:
        # pre- AND post-rewrite node counts per rewritten segment: post
        # alone would read as "capture got worse" next to
        # mean_ops_per_segment; shrink_ratio is the fraction of replay
        # nodes the rewriter removed (docs/faq/perf.md "Reading rewrite
        # telemetry")
        pre = out["counters"].get("lazy.rewrite.nodes_pre", 0)
        post = out["counters"].get("lazy.rewrite.nodes_post", 0)
        out["derived"]["lazy.rewrite.mean_ops_pre"] = pre / rseg
        out["derived"]["lazy.rewrite.mean_ops_post"] = post / rseg
        if pre > 0:
            out["derived"]["lazy.rewrite.shrink_ratio"] = (pre - post) / pre
    try:
        from . import compile_cache as _cc

        # per-name compile ledger: op-level (op_eager/op_vjp), lazy
        # segments, executors and the serving/generation planes — one
        # accounting language (tools/telemetry_report.py renders it)
        totals = _cc.name_totals()
        if totals:
            out["compile_caches"] = totals
    except Exception:  # noqa: BLE001 — snapshot must never fail
        pass
    try:
        # last computed roofline summary, by reference only (sys.modules:
        # a snapshot must never import — let alone probe — the
        # observatory; tools/telemetry_report.py renders the section)
        obs = sys.modules.get("mxnet_tpu.observatory")
        if obs is not None:
            cached = obs.cached_summary()
            if cached:
                out["observatory"] = cached
    except Exception:  # noqa: BLE001 — snapshot must never fail
        pass
    return out


def dumps(indent=2):
    """JSON snapshot of the registry."""
    return json.dumps(snapshot(), indent=indent)


def dump(path=None):
    """Write :func:`dumps` to ``path`` (default ``MXNET_TELEMETRY_DUMP``)
    through the checkpoint writers' temp-file + fsync + atomic-rename
    sequence — a reader (or a crash) never sees a torn snapshot."""
    from .resilience import durable_replace

    path = path or getenv("MXNET_TELEMETRY_DUMP")
    if not path:
        raise ValueError("no dump path: pass one or set MXNET_TELEMETRY_DUMP")
    payload = dumps()
    tmp = path + ".tmp~"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    durable_replace(tmp, path)
    return path


def trace_counter_events(ts=None):
    """The registry as chrome-trace ``"C"`` (counter) events, for merging
    into ``profiler.dump()`` output: counters/gauges one series each,
    histograms a {p50, p99, count} series — metrics land on the same
    timeline as the host scopes and the XLA trace."""
    ts = time.time() * 1e6 if ts is None else ts
    pid = os.getpid()
    snap = snapshot()
    events = []

    def emit(name, args):
        events.append({"name": f"telemetry/{name}", "ph": "C",
                       "cat": "telemetry", "pid": pid, "tid": 0,
                       "ts": ts, "args": args})

    for name, v in snap["counters"].items():
        emit(name, {"value": v})
    for name, v in snap["gauges"].items():
        emit(name, {"value": v})
    for name, v in snap["derived"].items():
        emit(name, {"value": v})
    for name, h in snap["histograms"].items():
        if h["count"]:
            emit(name, {"p50": h["p50"], "p99": h["p99"],
                        "count": h["count"]})
    return events


def dumps_table(snap=None, sort_by="total"):
    """Render a snapshot (live registry when ``snap`` is None) in the
    ``profiler.dumps_aggregate`` table format, histograms extended with
    quantile columns — one visual language for both planes
    (`tools/telemetry_report.py` renders dumped files through this)."""
    snap = snapshot() if snap is None else snap
    lines = ["", "Telemetry Statistics:"]

    def section(title, hdr, rows):
        if not rows:
            return
        lines.append("")
        lines.append(title)
        lines.append("=" * len(title))
        lines.append(hdr)
        lines.append("-" * len(hdr))
        lines.extend(rows)

    def val(v):
        return f"{v:>16.1f}" if isinstance(v, float) else f"{v:>16}"

    fmt_cg = f"{'Name':<40}{'Value':>16}"
    section("counters", fmt_cg,
            [f"{n[:39]:<40}{val(v)}" for n, v in sorted(snap["counters"].items())])
    section("gauges", fmt_cg,
            [f"{n[:39]:<40}{val(v)}" for n, v in sorted(snap["gauges"].items())])
    section("derived", fmt_cg,
            [f"{n[:39]:<40}{v:>16.4f}" for n, v in sorted(snap["derived"].items())])

    hdr = (f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
           f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}"
           f"{'p50 (ms)':>12}{'p95 (ms)':>12}{'p99 (ms)':>12}")
    rows = []
    key_idx = {"count": "count", "total": "sum", "avg": "avg",
               "min": "min", "max": "max"}
    if sort_by not in key_idx:
        raise ValueError(f"sort_by must be one of {sorted(key_idx)}")
    hists = sorted(snap["histograms"].items(),
                   key=lambda kv: kv[1].get(key_idx[sort_by]) or 0,
                   reverse=True)
    for name, h in hists:
        if not h["count"]:
            continue

        def ms(v):
            return f"{v / 1e3:>12.4f}" if v is not None else f"{'-':>12}"

        rows.append(f"{name[:39]:<40}{h['count']:>12}{h['sum'] / 1e3:>14.4f}"
                    f"{ms(h['min'])}{ms(h['max'])}{ms(h['avg'])}"
                    f"{ms(h['p50'])}{ms(h['p95'])}{ms(h['p99'])}")
    section("histograms (us-valued, shown in ms)", hdr, rows)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Prometheus text export + the /metrics HTTP endpoint
# ---------------------------------------------------------------------------


def _prom_name(name):
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    n = "".join(out)
    return n if n[:1].isalpha() or n[:1] == "_" else "_" + n


def _prom_value(v):
    """A metric value in Prometheus text form, or None when the value is
    not representable (a gauge someone set to a string must be skipped,
    not emitted as an unparseable sample). Non-finite floats use the
    spec spellings ``+Inf`` / ``-Inf`` / ``NaN``."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if not isinstance(v, (int, float)):
        return None
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(v) if isinstance(v, float) else str(v)


def _prom_label(value):
    """A label VALUE escaped per the text exposition format: backslash,
    double-quote and newline are the three characters the parser cannot
    take raw."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_split(name):
    """Split a :func:`labeled` metric name into ``(base, labelstr)`` —
    ``"qos.admitted|class=batch|tenant=acme"`` becomes
    ``("qos.admitted", 'class="batch",tenant="acme"')``. Unlabeled names
    pass through with an empty label string."""
    if "|" not in name:
        return name, ""
    base, _, rest = name.partition("|")
    pairs = []
    for tok in rest.split("|"):
        k, _, v = tok.partition("=")
        pairs.append(f'{_prom_name(k)}="{_prom_label(v)}"')
    return base, ",".join(pairs)


def prom_text(refresh_memory=True):
    """The registry in Prometheus text exposition format (what the HTTP
    ``/metrics`` endpoint serves, scrapeable by any Prometheus-compatible
    collector). Counters/gauges/derived map 1:1 (names prefixed
    ``mxnet_``, dots to underscores); histograms render as summaries
    (p50/p95/p99 quantile series + ``_sum``/``_count``).
    ``refresh_memory`` runs a device-buffer census first so ``memory.*``
    gauges are live, not last-read."""
    if refresh_memory:
        try:
            from . import memory

            memory.update_gauges()
        except Exception:  # noqa: BLE001 — census must not break a scrape
            pass
    snap = snapshot()
    lines = []
    # labeled() series of one base name form ONE metric family: the
    # # TYPE header is emitted once per family, however many label sets
    # report under it (names sort adjacently, so families stay grouped)
    typed = set()

    def emit(name, kind, value):
        v = _prom_value(value)
        if v is None:
            # un-renderable (e.g. a gauge set to a string): a skipped
            # sample keeps the whole exposition parseable
            return
        base, labels = _prom_split(name)
        n = "mxnet_" + _prom_name(base)
        if (n, kind) not in typed:
            typed.add((n, kind))
            lines.append(f"# TYPE {n} {kind}")
        lines.append(f"{n}{{{labels}}} {v}" if labels else f"{n} {v}")

    for name, v in sorted(snap["counters"].items()):
        emit(name, "counter", v)
    for name, v in sorted(snap["gauges"].items()):
        emit(name, "gauge", v)
    for name, v in sorted(snap["derived"].items()):
        emit(name, "gauge", v)
    for name, h in sorted(snap["histograms"].items()):
        base, labels = _prom_split(name)
        n = "mxnet_" + _prom_name(base)
        if (n, "summary") not in typed:
            typed.add((n, "summary"))
            lines.append(f"# TYPE {n} summary")
        if h["count"]:
            for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                qv = _prom_value(h[key])
                if qv is None:
                    # a zero-size reservoir records count/sum but no
                    # quantiles — "None" is not a float the parser takes
                    continue
                lab = (f'{labels},quantile="{_prom_label(q)}"' if labels
                       else f'quantile="{_prom_label(q)}"')
                lines.append(f"{n}{{{lab}}} {qv}")
        suffix = f"{{{labels}}}" if labels else ""
        lines.append(f"{n}_sum{suffix} {_prom_value(h['sum'])}")
        lines.append(f"{n}_count{suffix} {_prom_value(h['count'])}")
    return "\n".join(lines) + "\n"


_http_server = None
_http_thread = None


def start_http_server(port=None, host=None):
    """Start the background observability endpoint (idempotent; opt-in via
    ``MXNET_TELEMETRY_HTTP_PORT`` or an explicit port; binds
    ``MXNET_TELEMETRY_HTTP_HOST``, loopback by default). Serves:

    * ``/metrics`` — :func:`prom_text` (Prometheus scrape format);
    * ``/trace``  — the current chrome-trace buffer (host spans + span
      tracing + telemetry counters, NOT reset by the read) plus the
      flight recorder's worst-step span tree;
    * ``/memory`` — the live device-buffer census
      (:func:`mxnet_tpu.memory.census`) + per-executable XLA memory
      analysis where computed;
    * ``/slo`` — the SLO tracker's evaluation report (objectives, burn
      rates, budget state, the autoscale signal);
    * ``/healthz`` / ``/readyz`` — liveness/readiness probe aggregation
      (HTTP 503 when any probe fails — a k8s-shaped contract);
    * ``/events`` — the health event journal (bounded ring of runtime
      events: rejections, evictions, drains, watchdog firings);
    * ``/roofline`` — the observatory's roofline report: measured device
      peaks + per-lane MFU/MBU attribution
      (:func:`mxnet_tpu.observatory.summary`).

    Returns the server (its ``.server_address[1]`` is the bound port —
    pass port 0 for an ephemeral one in tests), or None when off."""
    global _http_server, _http_thread
    if _http_server is not None:
        return _http_server
    if port is None:
        port = int(getenv("MXNET_TELEMETRY_HTTP_PORT"))
        if not port:
            return None
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet: not a user-facing web server
            pass

        def _send(self, body, ctype, code=200):
            data = body.encode() if isinstance(body, str) else body
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            try:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(prom_text(), "text/plain; version=0.0.4")
                elif path == "/trace":
                    from . import profiler, tracing

                    doc = profiler.peek_doc()
                    worst = tracing.flight_recorder.worst()
                    if worst is not None:
                        doc.setdefault("otherData", {})["worst_step"] = worst
                    # the generation analog: the worst scheduler decode
                    # tick's span tree (tracing.tick_recorder)
                    tick = tracing.tick_recorder.worst()
                    if tick is not None:
                        doc.setdefault("otherData", {})["worst_tick"] = tick
                    # compact: a near-cap buffer is hundreds of MB
                    # pretty-printed, and this is a machine-read endpoint
                    self._send(json.dumps(doc), "application/json")
                elif path == "/memory":
                    from . import memory

                    doc = memory.census()
                    doc["executables"] = memory.executable_stats()
                    self._send(json.dumps(doc, indent=2), "application/json")
                elif path == "/slo":
                    from . import health

                    self._send(json.dumps(health.slo_report(), indent=2,
                                          default=repr),
                               "application/json")
                elif path == "/healthz":
                    from . import health

                    ok, probes = health.liveness()
                    body = {"ok": ok, "pid": os.getpid(),
                            "health_enabled": health._enabled,
                            "probes": probes}
                    self._send(json.dumps(body, indent=2),
                               "application/json", 200 if ok else 503)
                elif path == "/readyz":
                    from . import health

                    ok, probes = health.readiness()
                    body = {"ok": ok, "probes": probes}
                    self._send(json.dumps(body, indent=2),
                               "application/json", 200 if ok else 503)
                elif path == "/events":
                    from . import health

                    self._send(json.dumps(health.events(), indent=2,
                                          default=repr),
                               "application/json")
                elif path == "/roofline":
                    from . import observatory

                    # summary() computes attribution for observed lanes —
                    # the first scrape after new compiles pays the lazy
                    # AOT cost pass (like /memory), never the step path
                    self._send(json.dumps(observatory.summary(), indent=2,
                                          default=repr),
                               "application/json")
                else:
                    self.send_error(404, "try /metrics, /trace, /memory, "
                                         "/slo, /healthz, /readyz, "
                                         "/events or /roofline")
            except Exception as e:  # noqa: BLE001 — a scrape must not crash
                try:
                    self.send_error(500, repr(e))
                except Exception:
                    pass

    host = host or getenv("MXNET_TELEMETRY_HTTP_HOST")
    _http_server = ThreadingHTTPServer((host, int(port)), Handler)
    _http_thread = threading.Thread(target=_http_server.serve_forever,
                                    daemon=True,
                                    name="mxnet_tpu.telemetry.http")
    _http_thread.start()
    _logger().info("telemetry HTTP endpoint on %s:%d "
                   "(/metrics, /trace, /memory)", host,
                   _http_server.server_address[1])
    return _http_server


def stop_http_server():
    global _http_server, _http_thread
    if _http_server is not None:
        _http_server.shutdown()
        _http_server.server_close()
        _http_server = None
    if _http_thread is not None:
        _http_thread.join(timeout=1.0)
        _http_thread = None


# ---------------------------------------------------------------------------
# Periodic log summaries
# ---------------------------------------------------------------------------

_log_thread = None
_log_stop = threading.Event()


def start_log_thread(interval=None):
    """Start the summary logger (idempotent). Interval from the arg or
    ``MXNET_TELEMETRY_LOG_INTERVAL_S``; 0/negative means off."""
    global _log_thread
    interval = (float(getenv("MXNET_TELEMETRY_LOG_INTERVAL_S"))
                if interval is None else float(interval))
    if interval <= 0 or (_log_thread is not None and _log_thread.is_alive()):
        return None
    _log_stop.clear()

    def loop():
        while not _log_stop.wait(interval):
            if _enabled and _registry:
                _logger().info("telemetry summary:%s", dumps_table())

    _log_thread = threading.Thread(target=loop, daemon=True,
                                   name="mxnet_tpu.telemetry.log")
    _log_thread.start()
    return _log_thread


def stop_log_thread():
    global _log_thread
    _log_stop.set()
    if _log_thread is not None:
        _log_thread.join(timeout=1.0)
        _log_thread = None


@atexit.register
def _dump_at_exit():
    """``MXNET_TELEMETRY_DUMP`` exit dump — best-effort: a failed telemetry
    write must never turn a clean exit into a crash, but it is logged."""
    path = getenv("MXNET_TELEMETRY_DUMP")
    if not path or not _registry:
        return
    try:
        dump(path)
    except Exception as e:  # noqa: BLE001 — interpreter is dying
        try:
            _logger().error("telemetry exit dump to %s failed: %r", path, e)
        except Exception:
            pass


if _enabled:
    start_log_thread()

if int(getenv("MXNET_TELEMETRY_HTTP_PORT") or 0):
    try:  # opt-in endpoint; a busy port must not break import
        start_http_server()
    except Exception as _e:  # noqa: BLE001
        _logger().error("telemetry HTTP endpoint failed to start: %r", _e)
