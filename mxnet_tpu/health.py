"""Fleet health: SLOs, liveness/readiness, stall watchdog, event journal.

PR 7 made the runtime *measurable* (telemetry counters, span tracing,
``/metrics`` / ``/trace`` / ``/memory``); nothing consumed those signals
at runtime — a wedged GenerationEngine kept receiving router placements
and a stalled training step died as an opaque hang. This module is the
layer that *acts* on the signals:

* **SLO tracker** (:class:`SloTracker`) — declarative objectives over the
  existing telemetry registry (``serving.generation.ttft_us:p99<500ms``,
  ``compile.cache_misses:rate<=0``, ``step.total_us:p99<8*p50``), parsed
  from ``MXNET_SLO_SPEC``, evaluated on rolling windows with multi-window
  error-budget burn rate (the SRE multi-burn-rate alerting shape: a short
  window for fast detection, a long window for budget exhaustion).
  Published as ``slo.*`` gauges and served at ``/slo`` next to
  ``/metrics``.
* **liveness / readiness registries** — per-object probes
  (:func:`register_liveness` / :func:`register_readiness`, weakly held)
  aggregated by :func:`liveness` / :func:`readiness` and served at
  ``/healthz`` / ``/readyz``. The serving layer registers every
  Predictor / DynamicBatcher / GenerationEngine; the
  ``GenerationRouter`` consults per-engine readiness to *drain* unready
  replicas (stop placing, let live sessions finish) and re-admit on
  recovery.
* **stall watchdog** — :class:`Beacon` progress markers on the paths that
  must make progress (generation scheduler tick, ``fit`` step, lazy
  segment flush). A beacon that is *armed* (work pending) but silent for
  longer than ``max(MXNET_HEALTH_STALL_FACTOR × rolling-median gap,
  MXNET_HEALTH_STALL_FLOOR_S)`` fires a one-shot **diagnostic capture**
  (:func:`capture_diagnostics`): all-thread stacks, the flight
  recorders' worst step/tick span trees, a telemetry snapshot, the
  compile-cache ledger and the event-journal tail, written atomically
  under ``MXNET_HEALTH_DIR`` and counted in ``health.stalls``. Recovery
  (the beacon progressing again) re-arms the capture.
* **event journal** — a bounded ring of structured runtime events the
  system already experiences but never recorded as a sequence
  (admission rejections, evictions by reason, engine drain/undrain,
  elastic shrink, lazy hysteresis trips, compile-cache evictions,
  watchdog firings). Served at ``/events`` and merged into
  ``profiler.dump()`` as chrome-trace instant events.
* **autoscale signal** — the ``health.desired_engines`` gauge derived
  from fleet slot-fill, queue depth and SLO burn
  (:func:`autoscale_signal`), plus :func:`on_autoscale` callbacks so an
  external controller can act on it.

Overhead discipline (the PR 7 rule): everything gates on the
module-level ``_enabled`` flag (``MXNET_HEALTH=1`` or :func:`enable`).
Instrumented call sites read ONE attribute when off — no timestamps, no
allocation, and no monitor threads are ever started
(``test_health.py`` pins the disabled path).
"""
from __future__ import annotations

import collections
import json
import os
import re
import sys
import tempfile
import threading
import time
import traceback
import weakref

from . import analysis
from . import telemetry
from .base import getenv, register_env
from .log import get_logger

__all__ = ["enabled", "enable", "disable", "reset",
           "event", "events", "trace_instant_events",
           "Beacon", "beacon", "beacons", "check_beacons",
           "capture_diagnostics", "last_bundle",
           "Objective", "SloTracker", "tracker", "slo_report", "budget_ok",
           "register_liveness", "register_readiness",
           "liveness", "readiness",
           "register_fleet", "on_autoscale", "autoscale_signal"]

register_env("MXNET_HEALTH", False,
             "enable the fleet-health layer: SLO tracker, liveness/"
             "readiness probes, stall watchdog + diagnostic capture, "
             "event journal, autoscale signal")
register_env("MXNET_HEALTH_DIR", "",
             "directory for watchdog diagnostic bundles (all-thread "
             "stacks + worst-step/tick trees + telemetry snapshot, "
             "written atomically); empty = <tmpdir>/mxnet_tpu_health")
register_env("MXNET_HEALTH_EVENTS", 512,
             "event-journal ring capacity (oldest events drop off)")
register_env("MXNET_HEALTH_WATCHDOG_S", 0.5,
             "stall-watchdog poll interval in seconds")
register_env("MXNET_HEALTH_STALL_FACTOR", 8.0,
             "a beacon armed but silent for longer than this multiple of "
             "its rolling-median progress gap is a stall")
register_env("MXNET_HEALTH_STALL_FLOOR_S", 5.0,
             "minimum silence before any beacon counts as stalled — "
             "sized to absorb a cold first-use XLA compile (a fresh "
             "prefill/step executable takes seconds), which is a pause, "
             "not a stall")
register_env("MXNET_HEALTH_QUEUE_WATERMARK", 0.8,
             "readiness watermark: a serving/generation intake queue "
             "above this fraction of MXNET_SERVING_MAX_QUEUE reports "
             "not-ready (the router stops placing there)")
register_env("MXNET_SLO_SPEC", "",
             "semicolon-separated SLO objectives over telemetry metrics, "
             "each `metric:stat op value[unit]` (stat p50/p95/p99/avg/"
             "min/max/count/rate/value; unit us/ms/s; value may be "
             "`K*p50` for a same-histogram multiple). Empty = the "
             "built-in serving/compile/step defaults")
register_env("MXNET_SLO_WINDOWS", "60,600",
             "short,long burn-rate windows in seconds (SRE multi-window "
             "pattern: short detects fast burn, long tracks budget "
             "exhaustion)")
register_env("MXNET_SLO_BUDGET", 0.01,
             "error budget: allowed fraction of violating evaluations "
             "per window (burn rate = violating fraction / this)")
register_env("MXNET_SLO_GRACE_S", 60.0,
             "rate-kind objectives (e.g. compile.cache_misses:rate<=0) "
             "pass vacuously for this long after tracker start — warmup "
             "compiles are not an SLO breach")
register_env("MXNET_SLO_INTERVAL_S", 5.0,
             "background SLO-evaluation cadence once health is enabled "
             "(0 = evaluate only on demand: /slo scrapes and tests)")
register_env("MXNET_HEALTH_TARGET_FILL", 0.75,
             "autoscale target: desired engine count sizes the fleet so "
             "demand / (slots * engines) approaches this fill ratio")

# THE gate — call sites read `health._enabled` (one attribute fetch)
# before any other health work, including timestamps.
_enabled = bool(getenv("MXNET_HEALTH"))

_lock = analysis.make_lock("health.registry")


def _logger():
    return get_logger("mxnet_tpu.health")


def enabled():
    return _enabled


def enable(on=True):
    """Turn the health layer on (also: ``MXNET_HEALTH=1`` at import).
    Enabling starts the watchdog (and, when ``MXNET_SLO_INTERVAL_S`` > 0,
    the SLO evaluation) thread; disabling parks them."""
    global _enabled
    _enabled = bool(on)
    if _enabled:
        _start_threads()


def disable():
    enable(False)


def reset():
    """Drop journal, beacons, probes, tracker and autoscale state
    (tests). The enabled flag and any running monitor thread are kept —
    a parked thread over empty registries costs nothing."""
    global _tracker, _last_bundle, _bundle_seq
    with _lock:
        _journal.clear()
        _beacons.clear()
        _liveness.clear()
        _readiness.clear()
        _fleets.clear()
        _autoscale_cbs.clear()
        _tracker = None
        _last_bundle = None
        _bundle_seq = 0
        _autoscale_state["desired"] = None


# ---------------------------------------------------------------------------
# Event journal
# ---------------------------------------------------------------------------

_journal = collections.deque(maxlen=int(getenv("MXNET_HEALTH_EVENTS")))


def event(kind, **detail):
    """Append one structured event to the bounded journal (no-op when the
    health layer is off — call sites gate on ``health._enabled`` first so
    the disabled cost is one attribute read)."""
    if not _enabled:
        return None
    ev = {"ts": time.time(), "kind": str(kind)}
    ev.update(detail)
    with _lock:
        _journal.append(ev)
    telemetry.counter("health.events").inc()
    return ev


def events(n=None, kind=None):
    """The journal, oldest first (``n`` caps to the newest n; ``kind``
    filters)."""
    with _lock:
        out = list(_journal)
    if kind is not None:
        out = [e for e in out if e["kind"] == kind]
    if n is not None:
        out = out[-int(n):]
    return out


def trace_instant_events():
    """The journal as chrome-trace instant (``"i"``) events, for merging
    into ``profiler.dump()`` — runtime events (evictions, drains,
    watchdog firings) land on the same timeline as spans and counters."""
    pid = os.getpid()
    out = []
    for ev in events():
        args = {k: v for k, v in ev.items() if k not in ("ts", "kind")}
        out.append({"name": f"health/{ev['kind']}", "ph": "i", "s": "p",
                    "cat": "health", "pid": pid, "tid": 0,
                    "ts": ev["ts"] * 1e6, "args": args})
    return out


# ---------------------------------------------------------------------------
# Progress beacons + the stall watchdog
# ---------------------------------------------------------------------------

_beacons = {}


class Beacon:
    """One progress marker the watchdog monitors.

    A beacon is **armed** while its owner has pending work (a submitted
    generation session, a training loop between steps, a captured lazy
    segment) and **touched** whenever progress happens (a scheduler tick,
    a completed step, a flush). Armed + silent past
    ``max(factor × rolling-median gap, floor)`` = stalled; progress after
    a stall is a recovery. Idle owners (nothing pending) are never
    stalls."""

    __slots__ = ("name", "_owner", "_lock", "last", "active", "stalled",
                 "gaps", "touches", "stall_count")

    WINDOW = 64  # rolling gap samples for the median

    def __init__(self, name, owner=None):
        self.name = name
        self._owner = weakref.ref(owner) if owner is not None else None
        self._lock = analysis.make_lock("health.beacon")
        self.last = None          # monotonic of the last progress
        self.active = False       # work pending (silence counts as stall)
        self.stalled = False      # set by the watchdog, cleared by touch()
        self.gaps = collections.deque(maxlen=self.WINDOW)
        self.touches = 0
        self.stall_count = 0

    @property
    def owner(self):
        return self._owner() if self._owner is not None else None

    def arm(self):
        """Mark work pending. An idle->armed transition RESTARTS the
        silence clock — the stale last-progress stamp of a beacon that
        idled an hour ago must not count as an hour of stall silence the
        moment new work arrives."""
        with self._lock:
            if not self.active:
                self.active = True
                self.last = time.monotonic()

    def touch(self):
        """Record progress. Returns True when this touch RECOVERED a
        stalled beacon (the caller may want to log/flip readiness)."""
        now = time.monotonic()
        with self._lock:
            if self.active and self.last is not None:
                self.gaps.append(now - self.last)
            self.last = now
            self.touches += 1
            recovered = self.stalled
            self.stalled = False
        if recovered:
            event("watchdog_recovered", beacon=self.name)
            telemetry.counter("health.recoveries").inc()
            _logger().warning("beacon %r recovered after stall", self.name)
        return recovered

    def idle(self):
        """No work pending: silence is not a stall anymore."""
        with self._lock:
            self.active = False
            self.stalled = False

    def median_gap(self):
        with self._lock:
            gaps = sorted(self.gaps)
        if not gaps:
            return None
        return gaps[len(gaps) // 2]

    def silence(self, now=None):
        """Seconds since the last progress (None when never touched)."""
        if self.last is None:
            return None
        return (time.monotonic() if now is None else now) - self.last

    def overdue(self, now, factor, floor):
        """Armed and silent past the stall threshold?"""
        with self._lock:
            if not self.active or self.last is None:
                return False
            silence = now - self.last
        med = self.median_gap()
        threshold = max(factor * med if med else 0.0, floor)
        return silence > threshold

    def snapshot(self):
        return {"name": self.name, "active": self.active,
                "stalled": self.stalled, "touches": self.touches,
                "silence_s": self.silence(),
                "median_gap_s": self.median_gap(),
                "stalls": self.stall_count}


def beacon(name, owner=None):
    """Get-or-create the beacon named ``name``. Creation is cheap (a tiny
    object in a dict) so owners may create beacons unconditionally at
    construction; only ``arm``/``touch`` calls are gated on
    ``health._enabled`` at the call site."""
    with _lock:
        b = _beacons.get(name)
        if b is None:
            if len(_beacons) > 256:
                # opportunistic bound: with the watchdog off (health
                # disabled) nothing else prunes dead-owner beacons, and
                # per-engine names are unique
                for k in [k for k, v in _beacons.items()
                          if v._owner is not None and v.owner is None]:
                    del _beacons[k]
            b = _beacons[name] = Beacon(name, owner)
        elif owner is not None:
            # re-bind: names can legitimately recur (lazy beacons are
            # keyed by thread id, which CPython recycles) — the latest
            # owner wins, or a dead-owner prune would silently drop a
            # beacon a LIVE owner still arms and touches
            b._owner = weakref.ref(owner)
        return b


def beacons():
    with _lock:
        return dict(_beacons)


def check_beacons(now=None):
    """One watchdog sweep: fire a diagnostic capture for every beacon
    that just became overdue (dead owners are unregistered instead).
    Returns the list of beacons that stalled THIS sweep — the monitor
    thread calls this every ``MXNET_HEALTH_WATCHDOG_S``; tests call it
    directly for determinism."""
    if not _enabled:
        return []
    now = time.monotonic() if now is None else now
    factor = float(getenv("MXNET_HEALTH_STALL_FACTOR"))
    floor = float(getenv("MXNET_HEALTH_STALL_FLOOR_S"))
    fired = []
    with _lock:
        items = list(_beacons.items())
    for name, b in items:
        if b._owner is not None and b.owner is None:
            with _lock:
                if _beacons.get(name) is b:
                    del _beacons[name]
            continue
        if b.stalled or not b.overdue(now, factor, floor):
            continue
        b.stalled = True
        b.stall_count += 1
        fired.append(b)
        telemetry.counter("health.stalls").inc()
        _logger().error(
            "beacon %r stalled: %.2fs silent (median gap %s, factor %.1f, "
            "floor %.1fs) — capturing diagnostics", name,
            b.silence(now) or 0.0, b.median_gap(), factor, floor)
        try:
            path = capture_diagnostics(f"stall:{name}", beacon=b)
        except Exception as e:  # noqa: BLE001 — the watchdog must survive
            path = None
            _logger().error("diagnostic capture failed: %r", e)
        event("watchdog_stall", beacon=name, bundle=path,
              silence_s=b.silence(now))
    return fired


# ---------------------------------------------------------------------------
# Diagnostic capture
# ---------------------------------------------------------------------------

_last_bundle = None
_bundle_seq = 0


def last_bundle():
    """Path of the most recent diagnostic bundle (None if none yet)."""
    return _last_bundle


def _health_dir():
    d = str(getenv("MXNET_HEALTH_DIR") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "mxnet_tpu_health")
    return d


def _thread_stacks():
    """{thread name/id: [frame lines]} for every live thread — the
    in-process rendering of a faulthandler dump, structured for the
    bundle JSON."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, '?')} (tid={tid})"
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


def capture_diagnostics(reason, beacon=None, extra=None):
    """One diagnostic bundle, written atomically to ``MXNET_HEALTH_DIR``:

    * all-thread stacks (``sys._current_frames``; a ``faulthandler``
      text dump rides next to the JSON as ``<bundle>.stacks.txt`` for
      the cases where JSON assembly itself would be the casualty),
    * the flight recorders' worst-step and worst-decode-tick span trees,
    * a full telemetry snapshot,
    * the compile-cache per-name ledger (``compile_cache.name_totals``),
    * the event-journal tail.

    Returns the bundle path. Counted in ``health.captures``."""
    global _last_bundle, _bundle_seq
    with _lock:
        _bundle_seq += 1
        seq = _bundle_seq
    doc = {"ts": time.time(), "pid": os.getpid(), "reason": str(reason),
           "threads": _thread_stacks()}
    if beacon is not None:
        doc["beacon"] = beacon.snapshot()
    try:
        from . import tracing

        doc["worst_step"] = tracing.flight_recorder.worst()
        doc["worst_tick"] = tracing.tick_recorder.worst()
    except Exception:  # noqa: BLE001 — every section is best-effort
        pass
    try:
        doc["telemetry"] = telemetry.snapshot()
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import compile_cache

        doc["compile_caches"] = compile_cache.name_totals()
    except Exception:  # noqa: BLE001
        pass
    doc["events"] = events(n=64)
    if extra:
        doc["extra"] = extra

    d = _health_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"stall-{os.getpid()}-{seq}.json")
    tmp = path + ".tmp~"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, default=repr)
        f.flush()
        os.fsync(f.fileno())
    try:
        from .resilience import durable_replace

        durable_replace(tmp, path)
    except Exception:  # noqa: BLE001 — plain rename is still atomic
        os.replace(tmp, path)
    try:
        import faulthandler

        with open(path + ".stacks.txt", "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
    except Exception:  # noqa: BLE001
        pass
    telemetry.counter("health.captures").inc()
    _last_bundle = path
    _logger().error("diagnostic bundle written: %s (%s)", path, reason)
    return path


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------

_DEFAULT_SPEC = ("serving.generation.ttft_us:p99<500ms;"
                 "serving.e2e_us:p99<250ms;"
                 "compile.cache_misses:rate<=0;"
                 "step.total_us:p99<8*p50;"
                 # MFU collapse: achieved step FLOP/s under 0.1% of the
                 # MEASURED matmul peak (observatory.summary publishes
                 # step.mfu) means the step path stopped doing real work
                 # per wall second — a bug, not a ceiling, on any backend
                 "step.mfu:value>=0.001;"
                 # projected peak-HBM headroom went negative: resident
                 # census + the worst warmed executable's temp working
                 # set exceed device capacity (memory.census) — the next
                 # dispatch of that program OOMs even though today's
                 # resident bytes still fit
                 "memory.headroom_bytes:value>=0")

_OBJ_RE = re.compile(
    r"^(p\d{1,2}|avg|min|max|count|rate|value)\s*"
    r"(<=|>=|==|!=|<|>)\s*(.+)$")
_VAL_RE = re.compile(r"^([0-9]*\.?[0-9]+)\s*(us|ms|s)?$")
_REL_RE = re.compile(r"^([0-9]*\.?[0-9]+)\s*\*\s*(p\d{1,2}|avg)$")

_UNIT_US = {"us": 1.0, "ms": 1e3, "s": 1e6}

_OPS = {"<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
        "==": lambda a, b: a == b, "!=": lambda a, b: a != b}


class Objective:
    """One parsed SLO objective: ``metric:stat op value[unit]``.

    ``stat`` selects how the metric is read — a histogram quantile/field
    (``p99``/``avg``/``min``/``max``/``count``), a counter ``rate``
    (delta per second between evaluations) or the raw gauge/counter
    ``value``. The threshold may reference the SAME histogram
    (``8*p50``) for relative objectives like "no step slower than 8× the
    rolling median"."""

    def __init__(self, spec):
        self.spec = spec.strip()
        try:
            metric, rest = self.spec.split(":", 1)
        except ValueError:
            raise ValueError(
                f"SLO objective {spec!r}: expected 'metric:stat op value'")
        m = _OBJ_RE.match(rest.strip())
        if not m:
            raise ValueError(
                f"SLO objective {spec!r}: bad stat/comparison {rest!r} "
                "(stat one of pNN/avg/min/max/count/rate/value)")
        self.metric = metric.strip()
        self.stat, self.op = m.group(1), m.group(2)
        val = m.group(3).strip()
        rel = _REL_RE.match(val)
        if rel:
            self.threshold = float(rel.group(1))
            self.rel_stat = rel.group(2)
        else:
            v = _VAL_RE.match(val)
            if not v:
                raise ValueError(
                    f"SLO objective {spec!r}: bad threshold {val!r}")
            self.threshold = float(v.group(1)) * _UNIT_US.get(v.group(2), 1.0)
            self.rel_stat = None
        # labeled metric names (QoS per-tenant rows like
        # ``qos.ttft_us|tenant=acme``) carry ``|``/``=`` — sanitized here
        # so the key stays a clean telemetry-name segment
        # (``slo.<key>.burn_short`` gauges, report rows)
        self.key = (f"{self.metric}_{self.stat}".replace("*", "x")
                    .replace("|", ".").replace("=", "_"))

    def _hist_field(self, h, stat):
        if stat.startswith("p"):
            q = {"p50": "p50", "p95": "p95", "p99": "p99"}.get(stat)
            if q is not None:
                return h.get(q)
            # off-grid quantile: fall back to the nearest snapshot field
            qn = int(stat[1:])
            return h.get("p50" if qn <= 72 else "p95" if qn <= 97
                         else "p99")
        return h.get(stat)

    def evaluate(self, snap, rates):
        """(value, ok, threshold) against one telemetry snapshot.
        ``ok`` is True vacuously when the metric has no data yet — an
        objective over traffic that never happened is not a breach."""
        value = None
        threshold = self.threshold
        if self.stat == "rate":
            value = rates.get(self.metric)
        elif self.stat == "value":
            value = snap["gauges"].get(self.metric)
            if value is None:
                value = snap["counters"].get(self.metric)
        else:
            h = snap["histograms"].get(self.metric)
            if h and h.get("count"):
                value = self._hist_field(h, self.stat)
                if self.rel_stat is not None:
                    ref = self._hist_field(h, self.rel_stat)
                    threshold = (self.threshold * ref
                                 if ref is not None else None)
        if value is None or threshold is None:
            return None, True, threshold
        return value, _OPS[self.op](value, threshold), threshold


def parse_spec(spec=None):
    """``MXNET_SLO_SPEC`` (or the built-in defaults) as a list of
    :class:`Objective`."""
    spec = getenv("MXNET_SLO_SPEC") if spec is None else spec
    spec = (spec or "").strip() or _DEFAULT_SPEC
    return [Objective(tok) for tok in spec.split(";") if tok.strip()]


class SloTracker:
    """Rolling evaluation of a set of objectives with multi-window
    error-budget burn rates.

    Every :meth:`evaluate` records one (ts, ok) sample per objective;
    the burn rate over a window is ``violating fraction / budget`` — a
    burn of 1.0 consumes exactly the budget, >1 is on track to exhaust
    it, and the LONG window at >= 1 means the budget is spent
    (:attr:`exhausted`, which readiness consults). Gauges published per
    objective: ``slo.<key>.ok`` / ``.burn_short`` / ``.burn_long``,
    plus the overall ``slo.healthy``."""

    def __init__(self, objectives=None, windows=None, budget=None,
                 grace_s=None):
        self.objectives = (parse_spec() if objectives is None
                           else list(objectives))
        if windows is None:
            toks = str(getenv("MXNET_SLO_WINDOWS")).split(",")
            windows = tuple(float(t) for t in toks if t.strip())[:2]
        if len(windows) != 2 or windows[0] <= 0 or windows[1] < windows[0]:
            raise ValueError(f"need short,long SLO windows, got {windows}")
        self.windows = tuple(windows)
        self.budget = float(getenv("MXNET_SLO_BUDGET")
                            if budget is None else budget)
        self.grace_s = float(getenv("MXNET_SLO_GRACE_S")
                             if grace_s is None else grace_s)
        self.started_at = time.monotonic()
        self._samples = {o.key: collections.deque()
                         for o in self.objectives}
        self._last_counters = {}
        self._last_ts = None
        self._lock = analysis.make_lock("health.slo")
        self.evaluations = 0
        self.exhausted = False

    def _rates(self, snap, now):
        """Per-counter delta/dt since the previous evaluation (first
        evaluation yields no rates)."""
        rates = {}
        counters = snap["counters"]
        if self._last_ts is not None:
            dt = max(now - self._last_ts, 1e-9)
            for name, v in counters.items():
                # a counter ABSENT from the previous snapshot was 0 then
                # (counters are monotonic from 0) — skipping it instead
                # would hide exactly the increment that created it, i.e.
                # the first stall/miss ever, the one that matters most
                rates[name] = (v - self._last_counters.get(name, 0)) / dt
        self._last_counters = dict(counters)
        self._last_ts = now
        return rates

    def _burn(self, samples, now, window):
        """(burn, n) over one window; burn None when no samples."""
        lo = now - window
        total = bad = 0
        for ts, ok in samples:
            if ts >= lo:
                total += 1
                bad += 0 if ok else 1
        if not total:
            return None, 0
        return (bad / total) / max(self.budget, 1e-9), total

    def evaluate(self, snap=None, now=None):
        """One evaluation pass: read the registry, score every objective,
        roll the windows, publish the ``slo.*`` gauges. Returns the
        report dict (also what ``/slo`` serves)."""
        now = time.monotonic() if now is None else now
        snap = telemetry.snapshot() if snap is None else snap
        with self._lock:
            rates = self._rates(snap, now)
            in_grace = (now - self.started_at) < self.grace_s
            self.evaluations += 1
            report = {"budget": self.budget,
                      "windows_s": list(self.windows),
                      "evaluations": self.evaluations,
                      "in_grace": in_grace,
                      "objectives": []}
            healthy = True
            exhausted = False
            for o in self.objectives:
                value, ok, threshold = o.evaluate(snap, rates)
                if o.stat == "rate" and in_grace:
                    # warmup compiles (and their ilk) are not a breach
                    ok = True
                samples = self._samples[o.key]
                samples.append((now, ok))
                lo = now - self.windows[1]
                while samples and samples[0][0] < lo:
                    samples.popleft()
                burn_s, n_s = self._burn(samples, now, self.windows[0])
                burn_l, n_l = self._burn(samples, now, self.windows[1])
                healthy = healthy and ok
                if burn_l is not None and burn_l >= 1.0:
                    exhausted = True
                report["objectives"].append({
                    "spec": o.spec, "key": o.key, "value": value,
                    "threshold": threshold, "ok": ok,
                    "burn_short": burn_s, "burn_long": burn_l,
                    "samples": n_l})
                telemetry.gauge(f"slo.{o.key}.ok").set(1 if ok else 0)
                if burn_s is not None:
                    telemetry.gauge(f"slo.{o.key}.burn_short").set(burn_s)
                if burn_l is not None:
                    telemetry.gauge(f"slo.{o.key}.burn_long").set(burn_l)
            self.exhausted = exhausted
            report["healthy"] = healthy
            report["exhausted"] = exhausted
            telemetry.gauge("slo.healthy").set(1 if healthy else 0)
            telemetry.gauge("slo.budget_exhausted").set(
                1 if exhausted else 0)
        return report


_tracker = None


def tracker():
    """The process SLO tracker (built lazily from ``MXNET_SLO_SPEC``)."""
    global _tracker
    if _tracker is None:
        with _lock:
            if _tracker is None:
                _tracker = SloTracker()
    return _tracker


def slo_report():
    """Evaluate now and return the report (the ``/slo`` endpoint body).
    ``{"enabled": False}`` when the health layer is off."""
    if not _enabled:
        return {"enabled": False}
    report = tracker().evaluate()
    report["enabled"] = True
    report["stalls"] = telemetry.counter("health.stalls").value
    report["desired_engines"] = autoscale_signal()
    return report


def budget_ok():
    """False once the long-window error budget is exhausted (readiness
    consults this; True when health is off or nothing evaluated yet)."""
    t = _tracker
    return t is None or not t.exhausted


# ---------------------------------------------------------------------------
# Liveness / readiness registries
# ---------------------------------------------------------------------------

# name -> (weakref(owner), probe). probe(owner) returns (ok, detail) or a
# plain bool. Dead owners drop out at read time.
_liveness = {}
_readiness = {}


def register_liveness(name, owner, probe):
    with _lock:
        _liveness[name] = (weakref.ref(owner), probe)


def register_readiness(name, owner, probe):
    with _lock:
        _readiness[name] = (weakref.ref(owner), probe)


def unregister(name):
    """Remove ``name`` from both probe registries (a deliberately closed
    server is no longer a serving participant — its drain must not pin
    the process ``/readyz`` false forever)."""
    with _lock:
        _liveness.pop(name, None)
        _readiness.pop(name, None)


def _run_probes(registry):
    with _lock:
        items = list(registry.items())
    ok_all = True
    out = {}
    for name, (ref, probe) in items:
        owner = ref()
        if owner is None:
            with _lock:
                if registry.get(name) == (ref, probe):
                    del registry[name]
            continue
        try:
            r = probe(owner)
        except Exception as e:  # noqa: BLE001 — a probe bug is "not ok"
            r = (False, f"probe error: {e!r}")
        ok, detail = r if isinstance(r, tuple) else (bool(r), "")
        out[name] = {"ok": bool(ok), "detail": detail}
        ok_all = ok_all and bool(ok)
    return ok_all, out


def liveness():
    """(ok, {probe: {ok, detail}}): process up + every registered
    liveness probe (scheduler/worker threads alive). An empty registry is
    alive — the process answered. With the health layer OFF the probes
    are not consulted (a deployment that only wanted /metrics must not
    grow new 503s from probes it never opted into)."""
    if not _enabled:
        return True, {}
    return _run_probes(_liveness)


def readiness():
    """(ok, {probe: ...}): every readiness probe (warmup complete, queue
    below watermark) AND the SLO error budget not exhausted. Trivially
    ready when the health layer is off (same opt-in rule as
    :func:`liveness`)."""
    if not _enabled:
        return True, {}
    ok, probes = _run_probes(_readiness)
    if not budget_ok():
        probes["slo.budget"] = {"ok": False,
                                "detail": "long-window error budget "
                                          "exhausted"}
        ok = False
    return ok, probes


# ---------------------------------------------------------------------------
# Autoscale signal
# ---------------------------------------------------------------------------

_fleets = []          # weakrefs to objects exposing .engines
_autoscale_cbs = []
_autoscale_state = {"desired": None}


def register_fleet(fleet):
    """Register an engine fleet (anything with ``.engines``, e.g. a
    :class:`~mxnet_tpu.serving.generation.router.GenerationRouter`) as an
    autoscale source. Weakly held."""
    with _lock:
        _fleets.append(weakref.ref(fleet))


def on_autoscale(cb):
    """Register ``cb(desired, info)`` — fired whenever the computed
    ``health.desired_engines`` CHANGES (the hook an external controller
    plugs into). Returns ``cb`` for decorator use."""
    with _lock:
        _autoscale_cbs.append(cb)
    return cb


def autoscale_signal(engines=None):
    """Compute the desired engine count from live fleet state: demand
    (live + queued sessions) over capacity at the target fill ratio,
    bumped one replica when the SLO short-window burn is over budget.
    Publishes ``health.desired_engines`` and fires the
    :func:`on_autoscale` callbacks on change. Returns the desired count
    (None when no fleet/engines are registered)."""
    if engines is None:
        engines = []
        with _lock:
            _fleets[:] = [r for r in _fleets if r() is not None]
            refs = list(_fleets)
        for ref in refs:
            f = ref()
            if f is not None:
                engines.extend(f.engines)
    engines = list(engines)
    if not engines:
        return None
    n = len(engines)
    # QoS active: demand is fairness-WEIGHTED (an interactive session
    # votes harder for replicas than a batch one — the fleet scales for
    # its latency-sensitive load, not its backlog); engines without the
    # hook (or with QoS off → qos_demand() is None) fall back to the raw
    # live + queued count, so the signal is unchanged by default
    demand = 0.0
    for e in engines:
        w = (e.qos_demand() if hasattr(e, "qos_demand") else None)
        demand += (e.live_slots + e.queue_depth) if w is None else w
    slots = sum(e.max_slots for e in engines) / n
    fill = float(getenv("MXNET_HEALTH_TARGET_FILL"))
    desired = max(1, -(-demand // max(slots * fill, 1e-9)))
    desired = int(desired)
    burning = False
    t = _tracker
    if t is not None:
        with t._lock:
            for key in t._samples:
                g = telemetry.get(f"slo.{key}.burn_short")
                if g is not None and g.value is not None \
                        and g.value > 1.0:
                    burning = True
                    break
    if burning:
        desired = max(desired, n + 1)
    telemetry.gauge("health.desired_engines").set(desired)
    info = {"engines": n, "demand": demand, "slots_per_engine": slots,
            "target_fill": fill, "slo_burning": burning}
    with _lock:
        changed = _autoscale_state["desired"] != desired
        _autoscale_state["desired"] = desired
        cbs = list(_autoscale_cbs)
    if changed:
        event("autoscale", desired=desired, **info)
        for cb in cbs:
            try:
                cb(desired, info)
            except Exception as e:  # noqa: BLE001 — a controller bug must
                _logger().error("autoscale callback failed: %r", e)
    return desired


# ---------------------------------------------------------------------------
# Monitor threads
# ---------------------------------------------------------------------------

_watchdog_thread = None
_slo_thread = None
_threads_lock = analysis.make_lock("health.threads")


def _watchdog_loop():
    while True:
        time.sleep(max(float(getenv("MXNET_HEALTH_WATCHDOG_S")), 0.05))
        if not _enabled:
            continue
        try:
            check_beacons()
        except Exception as e:  # noqa: BLE001 — the watchdog never dies
            _logger().error("watchdog sweep failed: %r", e)


def _slo_loop(interval):
    while True:
        time.sleep(interval)
        if not _enabled:
            continue
        try:
            tracker().evaluate()
            autoscale_signal()
        except Exception as e:  # noqa: BLE001
            _logger().error("SLO evaluation failed: %r", e)


def _start_threads():
    """Start the watchdog (and optional SLO) daemon threads once. Only
    ever called from :func:`enable` — with ``MXNET_HEALTH`` off no thread
    exists (pinned by test_health.py)."""
    global _watchdog_thread, _slo_thread
    with _threads_lock:
        if _watchdog_thread is None or not _watchdog_thread.is_alive():
            _watchdog_thread = threading.Thread(
                target=_watchdog_loop, daemon=True,
                name="mxnet_tpu.health.watchdog")
            _watchdog_thread.start()
        interval = float(getenv("MXNET_SLO_INTERVAL_S"))
        if interval > 0 and (_slo_thread is None
                             or not _slo_thread.is_alive()):
            _slo_thread = threading.Thread(
                target=_slo_loop, args=(interval,), daemon=True,
                name="mxnet_tpu.health.slo")
            _slo_thread.start()


if _enabled:
    _start_threads()
