"""Autograd — imperative differentiation with MXNet semantics.

Parity: `python/mxnet/autograd.py` (record/pause/train_mode/predict_mode
scopes :122-194, mark_variables :216, backward :243, grad :270, Function
:365) over the reference's tape in `src/imperative/imperative.cc`
(RecordOp / Backward).

TPU-native design: instead of building an NNVM backward graph, every
recorded op stores the **pullback** returned by `jax.vjp` (compiled together
with the forward — see `ops.registry.invoke_with_vjp`). `backward()` walks
the tape in reverse applying pullbacks; each pullback application is itself
a jit-cached XLA program. Hybridized blocks record a single tape node whose
pullback is the whole-graph backward — the analogue of CachedOp::Backward
(`src/imperative/cached_op.cc:1160`).
"""
from __future__ import annotations

import threading

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording", "is_training",
    "set_recording", "set_training", "mark_variables", "backward", "grad", "Function",
]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        # id(NDArray) -> [tape nodes referencing it]: lets a recorded
        # in-place write retarget only the nodes that actually touch the
        # array (O(uses), not O(tape)). Ids stay valid while indexed: the
        # node input/output lists hold strong references.
        _state.tape_index = {}
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train_mode):
    prev = _st().training
    _st().training = bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
            if self._enter_is_record:
                st = _st()
                st.scope_depth = getattr(st, "scope_depth", 0) + 1
                # fresh OUTERMOST record scope starts a fresh tape (a previous
                # scope never backward()ed would otherwise leak nodes); a
                # record nested inside pause() must NOT wipe the outer tape.
                if st.scope_depth == 1 and not self._prev_is_record:
                    _clear_tape()
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, ptype, value, trace):
        if self._enter_is_record is not None:
            if self._enter_is_record:
                st = _st()
                st.scope_depth = max(0, getattr(st, "scope_depth", 1) - 1)
            if self._prev_is_record != self._enter_is_record:
                set_recording(self._prev_is_record)
        if self._enter_train_mode is not None and self._prev_train_mode != self._enter_train_mode:
            set_training(self._prev_train_mode)


def record(train_mode=True):
    """Scope: ops executed inside are recorded on the tape."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------


class _RowSparseCT:
    """A row-sparse cotangent flowing through the tape: (indices, rows) of a
    logically-dense grad. The reference expresses this as a row_sparse
    NDArray chosen by FInferStorageType (`include/mxnet/op_attr_types.h`
    FInferStorageType; Embedding's sparse grad `indexing_op.cc`); here it is
    the tape value type, deduplicated lazily at deposit time so chained
    accumulations stay O(touched rows)."""

    __slots__ = ("indices", "data", "shape", "dtype")

    def __init__(self, indices, data, shape, dtype):
        self.indices = indices      # int32 (k,)
        self.data = data            # (k, *shape[1:])
        self.shape = tuple(shape)
        self.dtype = dtype

    def __add__(self, other):
        if other is None or (isinstance(other, int) and other == 0):
            return self
        if isinstance(other, _RowSparseCT):
            return _RowSparseCT(jnp.concatenate([self.indices, other.indices]),
                                jnp.concatenate([self.data, other.data]),
                                self.shape, self.dtype)
        return self.densify() + other

    __radd__ = __add__

    def densify(self):
        out = jnp.zeros(self.shape, self.dtype)
        if self.indices.size:
            out = out.at[self.indices].add(self.data)
        return out

    def dedup(self):
        """(unique_rows, summed_data) — the canonical row_sparse form."""
        uniq, inv = jnp.unique(self.indices, return_inverse=True)
        summed = jax.ops.segment_sum(self.data, inv.reshape(-1),
                                     num_segments=uniq.shape[0])
        return uniq, summed


class _TapeNode:
    __slots__ = ("vjp", "inputs", "outputs", "out_avals")

    def __init__(self, vjp, inputs, outputs, out_avals):
        self.vjp = vjp            # tree_util.Partial pullback (device residuals)
        self.inputs = inputs      # list[NDArray|None] aligned with fn args
        self.outputs = outputs    # list[NDArray] (user outputs, prefix of avals)
        self.out_avals = out_avals  # ShapeDtypeStruct for ALL fn outputs


def _record_node(vjp, inputs, outputs, out_avals):
    st = _st()
    node = _TapeNode(vjp, inputs, outputs, out_avals)
    st.tape.append(node)
    idx = st.tape_index
    for a in list(inputs) + list(outputs):
        if a is not None:
            idx.setdefault(id(a), []).append(node)


def _retarget(frm, to):
    """Swap every tape reference to `frm` for `to` — the identity rewrite
    behind NDArray._recorded_setitem (the pre-write value becomes its own
    tape identity). O(nodes using frm) via the tape index."""
    st = _st()
    nodes = st.tape_index.pop(id(frm), [])
    for node in nodes:
        node.inputs = [to if a is frm else a for a in node.inputs]
        node.outputs = [to if a is frm else a for a in node.outputs]
    if nodes:
        st.tape_index.setdefault(id(to), []).extend(nodes)


def _clear_tape():
    _st().tape = []
    _st().tape_index = {}


def mark_variables(variables, gradients, grad_reqs="write"):
    """Parity `autograd.py:216`: associate grad buffers with arrays."""
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v.grad = g
        v.grad_req = req
        v._ag_marked = True


def _zero_ct(aval):
    if jnp.issubdtype(aval.dtype, jnp.floating) or jnp.issubdtype(aval.dtype, jnp.complexfloating):
        return jnp.zeros(aval.shape, aval.dtype)
    return _np.zeros(aval.shape, jax.dtypes.float0)


def _run_backward(heads, head_grads, retain_graph, deposit=True):
    tape = _st().tape
    grad_map = {}  # id(NDArray) -> jnp cotangent

    for h, hg in zip(heads, head_grads):
        if hg is None:
            hg = jnp.ones(h.shape, h.dtype)
        else:
            hg = hg._data if hasattr(hg, "_data") else jnp.asarray(hg)
        grad_map[id(h)] = grad_map.get(id(h), 0) + hg

    for node in reversed(tape):
        if not any(id(o) in grad_map for o in node.outputs):
            continue
        cts = []
        for i, aval in enumerate(node.out_avals):
            if i < len(node.outputs) and id(node.outputs[i]) in grad_map:
                g = grad_map[id(node.outputs[i])]
                if isinstance(g, _RowSparseCT):
                    g = g.densify()  # a pullback consumes dense cotangents
                if getattr(g, "dtype", None) != aval.dtype:
                    g = jnp.asarray(g, aval.dtype)  # else: already usable
                cts.append(g)
            else:
                cts.append(_zero_ct(aval))
        cts = tuple(cts) if len(node.out_avals) > 1 else cts[0]
        if isinstance(node.vjp, _PyPullback):
            in_cts = node.vjp(cts)
        else:
            from .ops.registry import run_vjp

            in_cts = run_vjp(node.vjp, cts)
        for nd_in, ct in zip(node.inputs, in_cts):
            if nd_in is None or ct is None:
                continue
            if hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0:
                continue
            prev = grad_map.get(id(nd_in))
            grad_map[id(nd_in)] = ct if prev is None else prev + ct

    # deposit into marked variables honoring grad_req
    if deposit:
        for node in tape:
            for nd_in in node.inputs:
                _deposit(nd_in, grad_map)
        for h in heads:
            _deposit(h, grad_map)

    if not retain_graph:
        _clear_tape()
    return grad_map


def _deposit(nd_in, grad_map):
    from .ndarray.ndarray import NDArray
    from .ndarray.sparse import RowSparseNDArray

    if nd_in is None or not getattr(nd_in, "_ag_marked", False):
        return
    g = grad_map.get(id(nd_in))
    if g is None or nd_in.grad is None:
        return
    if isinstance(g, _RowSparseCT) and isinstance(nd_in.grad, RowSparseNDArray):
        # sparse cotangent into a row_sparse grad buffer: never densify
        uniq, summed = g.dedup()
        if nd_in.grad_req == "add" and nd_in.grad.indices.size:
            old = nd_in.grad
            cat = _RowSparseCT(
                jnp.concatenate([old.indices._data.astype(jnp.int32), uniq]),
                jnp.concatenate([old.data._data, summed.astype(old.data.dtype)]),
                g.shape, g.dtype)
            uniq, summed = cat.dedup()
        nd_in.grad._aux = {"data": NDArray(summed.astype(nd_in.grad.dtype)),
                           "indices": NDArray(uniq.astype(jnp.int32))}
        nd_in.grad._dense_cache = None
        nd_in.grad._aux_stale = False
    else:
        if isinstance(g, _RowSparseCT):
            g = g.densify()
        # avoid a per-parameter re-wrap dispatch when the cotangent already
        # has the right dtype (the common case: ~#params calls per step)
        if getattr(g, "dtype", None) != nd_in.grad.dtype:
            g = jnp.asarray(g, nd_in.grad.dtype)
        if nd_in.grad_req == "write":
            nd_in.grad._data = g
        elif nd_in.grad_req == "add":
            nd_in.grad._data = nd_in.grad._data + g
    nd_in._fresh_grad = True  # cleared by Trainer._update (stale-grad check)
    grad_map[id(nd_in)] = None  # only deposit once


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. all marked variables
    (parity `autograd.py:243` → MXAutogradBackwardEx)."""
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    _run_backward(heads, head_grads, retain_graph)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return grads of heads w.r.t. variables without touching .grad buffers
    (parity `autograd.py:270`). create_graph (2nd order) is not yet supported
    on the eager tape — use hybridized blocks + jax.grad composition."""
    from .ndarray.ndarray import NDArray

    if create_graph:
        raise MXNetError("create_graph=True is not supported on the eager tape; "
                         "hybridize and compose jax.grad instead")
    if isinstance(heads, NDArray):
        heads = [heads]
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    if retain_graph is None:
        retain_graph = create_graph

    grad_map = _run_backward(heads, head_grads, retain_graph=True, deposit=False)
    outs = []
    for v in variables:
        g = grad_map.get(id(v))
        if g is None:
            raise MXNetError("Cannot differentiate with respect to a variable the heads "
                             "do not depend on")
        if isinstance(g, _RowSparseCT):
            g = g.densify()
        outs.append(NDArray(jnp.asarray(g, v.dtype), v._ctx))
    if not retain_graph:
        _clear_tape()
    return outs[0] if single else outs


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported; use HybridBlock.export")


class Function:
    """Custom differentiable function (parity `autograd.py:365`).

    Subclass and implement ``forward``/``backward`` with NDArrays. The op is
    recorded as one tape node whose pullback calls the user's backward under
    pause().
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            def pullback(cts):
                cts_nd = [NDArray(jnp.asarray(c), outs[0]._ctx) for c in (cts if isinstance(cts, tuple) else (cts,))]
                with pause():
                    in_grads = func.backward(*cts_nd)
                if isinstance(in_grads, NDArray):
                    in_grads = [in_grads]
                return tuple(g._data if g is not None else None for g in in_grads)

            _record_node(
                _PyPullback(pullback),
                list(inputs),
                outs,
                [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs],
            )
        return outputs


class _PyPullback:
    """Wraps a python pullback so run_vjp's jit is bypassed (host callback)."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, cts):
        return self.fn(cts)
