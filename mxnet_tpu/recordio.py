"""RecordIO read/write.

Parity: `python/mxnet/recordio.py` (`MXRecordIO`, `MXIndexedRecordIO`,
`IRHeader` pack/unpack) over the dmlc-core RecordIO stream format the
reference consumes via `dmlc::RecordIOWriter/Reader` (SURVEY.md §2.2).

Byte-compatible with the reference format so `.rec` datasets produced by
the reference's `tools/im2rec` load unchanged:
  each record = [kMagic:u32][lrec:u32][data][pad to 4B]
  where lrec's upper 3 bits encode cflag (continue-flag for records split
  around the magic word; we write simple records, cflag=0) and lower 29
  bits the length.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a


class MXRecordIO:
    """Sequential RecordIO reader/writer (parity recordio.py:35)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.open()

    def open(self):
        from .resilience import open_checked

        if self.flag == "w":
            self.record = open_checked(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open_checked(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Override pickling behavior (DataLoader worker processes)."""
        d = dict(self.__dict__)
        d["record"] = None
        d["pid"] = None
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        self.open()

    def _check_pid(self, allow_reset=False):
        """Reopen after fork (reference resets handles in worker procs)."""
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in multiple processes")

    def close(self):
        if self.record is not None and not self.record.closed:
            self.record.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        """Insert a string buffer as a record."""
        assert self.writable
        self._check_pid(allow_reset=False)
        data = bytes(buf)
        self.record.write(struct.pack("<II", _kMagic, len(data) & 0x1FFFFFFF))
        self.record.write(data)
        pad = (4 - (len(data) % 4)) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def tell(self):
        assert self.writable
        return self.record.tell()

    def read(self):
        """Read a record as bytes, or None at EOF. Carries the `read`
        fault point; not auto-retried (a sequential read that partially
        consumed the stream is not idempotent — `read_idx` is the retried
        entry point)."""
        from .resilience import inject

        assert not self.writable
        self._check_pid(allow_reset=True)
        inject("read", self.uri)
        header = self.record.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        assert magic == _kMagic, "Invalid record magic"
        length = lrec & 0x1FFFFFFF
        cflag = lrec >> 29
        data = self.record.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.record.read(pad)
        if cflag != 0:
            # multi-part record: keep reading continuation parts
            parts = [data]
            while cflag in (1, 2):
                header = self.record.read(8)
                magic, lrec = struct.unpack("<II", header)
                assert magic == _kMagic
                length = lrec & 0x1FFFFFFF
                cflag = lrec >> 29
                part = self.record.read(length)
                pad = (4 - (length % 4)) % 4
                if pad:
                    self.record.read(pad)
                parts.append(part)
                if cflag == 3:
                    break
            data = b"".join(parts)
        return data


def read_all_records(uri):
    """All logical records of a RecordIO file as a list of bytes.

    Uses the native mmap scanner (`src/recordio.cc`) when `librt_tpu.so` is
    built — one C pass over the file instead of a python loop per record —
    and falls back to the python reader otherwise."""
    from . import lib

    try:
        native = lib.native_recordio(uri)
    except IOError:
        native = None
    if native is not None:
        try:
            return native.read_records()
        finally:
            native.close()
    reader = MXRecordIO(uri, "r")
    out = []
    while True:
        rec = reader.read()
        if rec is None:
            break
        out.append(rec)
    reader.close()
    return out


def list_record_offsets(uri):
    """Byte offsets of every logical record's frame HEADER (what
    MXIndexedRecordIO seeks to) — the index rec2idx builds (reference
    `tools/rec2idx.py` IndexCreator). Native scan when available; python
    re-scan otherwise. Returns a flat list of ints."""
    from . import lib

    try:
        native = lib.native_recordio(uri)
    except IOError:
        native = None
    if native is not None:
        try:
            offs = []
            for i in range(len(native)):
                c = int(native.cflags[i])
                if c in (0, 1):  # whole record or first frame of a split
                    offs.append(int(native.offsets[i]) - 8)
            return offs
        finally:
            native.close()
    reader = MXRecordIO(uri, "r")
    offs = []
    while True:
        pos = reader.record.tell()
        if reader.read() is None:
            break
        offs.append(pos)
    reader.close()
    return offs


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO via a .idx file of `key\\tposition` lines
    (parity recordio.py:160)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        if self.writable:
            self.fidx = open(self.idx_path, "w")

    def close(self):
        super().close()
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()

    def __getstate__(self):
        d = super().__getstate__()
        d["fidx"] = None
        return d

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        pos = self.idx[idx]
        self.record.seek(pos)

    def read_idx(self, idx):
        from .resilience import retry_call

        def attempt():
            self.seek(idx)
            return self.read()

        # seek+read restarts from the index offset, so a transient EIO
        # mid-record is safely replayed (flaky network filesystems)
        return retry_call(attempt, desc=f"read_idx({idx}) of {self.uri}")

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# header for images in the .rec files produced by tools/im2rec
# (parity recordio.py IRHeader :215)
IRHeader = __import__("collections").namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack (header, payload bytes) into a record string (parity :239)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record string into (header, payload) (parity :268)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack a record into header + decoded image ndarray (parity :291).
    Needs cv2 or PIL available; raises otherwise."""
    header, s = unpack(s)
    img = _imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image ndarray into a record (parity :316)."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def _imdecode(buf, iscolor):
    try:
        import cv2
        return cv2.imdecode(buf, iscolor)
    except ImportError:
        pass
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(buf.tobytes())))
        if img.ndim == 3:
            img = img[:, :, ::-1]  # RGB->BGR to match cv2 convention
        return img
    except ImportError as e:
        raise ImportError("unpack_img requires cv2 or PIL") from e


def _imencode(img, quality, img_fmt):
    try:
        import cv2
        jpg_formats = [".JPG", ".JPEG"]
        png_formats = [".PNG"]
        encode_params = None
        if img_fmt.upper() in jpg_formats:
            encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif img_fmt.upper() in png_formats:
            encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
        ret, buf = cv2.imencode(img_fmt, img, encode_params)
        assert ret, "failed to encode image"
        return buf.tobytes()
    except ImportError:
        pass
    try:
        from PIL import Image
        import io as _io
        arr = img[:, :, ::-1] if img.ndim == 3 else img  # BGR->RGB
        bio = _io.BytesIO()
        Image.fromarray(arr).save(bio, format=img_fmt.strip(".").upper().replace("JPG", "JPEG"),
                                  quality=quality)
        return bio.getvalue()
    except ImportError as e:
        raise ImportError("pack_img requires cv2 or PIL") from e
