"""Weight initializers.

Parity: `python/mxnet/initializer.py` — Zero/One/Constant/Uniform/Normal/
Orthogonal/Xavier/MSRAPrelu/Bilinear/LSTMBias/Mixed + registry and
InitDesc attribute-driven dispatch.
"""
from __future__ import annotations

import json
import re

import numpy as _np

# Library-owned RNG for host-side parameter initialization: reseeded by
# `mxnet_tpu.random.seed` WITHOUT touching the user's global numpy stream
# (the reference seeds per-context mxnet RNGs, likewise isolated).
_INIT_RNG = _np.random.RandomState()

from .base import MXNetError

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed", "Load",
           "register"]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    # also into the generic factory (reference initializer.py builds its
    # register/create on registry.py) so mx.registry views agree and the
    # JSON '[name, kwargs]' spec form works
    from .registry import get_register_func

    get_register_func(Initializer, "initializer")(klass)
    return klass


# frontend alias names (reference uses @mx.init.register alias decorators:
# `initializer.py` registers Zero as 'zeros', One as 'ones')
def _register_aliases():
    from .registry import get_alias_func

    for alias_, target in (("zeros", "zero"), ("ones", "one")):
        if target in _INIT_REGISTRY:
            _INIT_REGISTRY[alias_] = _INIT_REGISTRY[target]
            get_alias_func(Initializer, "initializer")(alias_)(
                _INIT_REGISTRY[target])


def get(name, *args, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform(0.07)
    key = name.lower()
    if key not in _INIT_REGISTRY:
        raise MXNetError(f"Unknown initializer {name}")
    return _INIT_REGISTRY[key](*args, **kwargs)


def register_named(name):
    """Register dynamically-built initializers (gluon Constant parameters)
    under an explicit key — mirrored into the generic registry so the JSON
    spec form resolves them too."""
    def deco(klass):
        _INIT_REGISTRY[name.lower()] = klass
        from .registry import get_alias_func

        get_alias_func(Initializer, "initializer")(name)(klass)
        return klass

    return deco


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (parity initializer.py:39)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("first argument must be a name string or InitDesc")
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            get(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _set(self, arr, np_val):
        arr[:] = np_val.astype(_np.dtype(arr.dtype)) if hasattr(np_val, "astype") else np_val

    def _init_zero(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_gamma(self, _, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_beta(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        raise MXNetError(
            f"Unknown initialization pattern for {name}; names must end with "
            "weight/bias/gamma/beta or register a custom pattern")


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, _np.ones(arr.shape))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, _np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, _INIT_RNG.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, _INIT_RNG.normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _INIT_RNG.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _INIT_RNG.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier initializer cannot init {name} with ndim<2")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _INIT_RNG.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, _INIT_RNG.normal(0, scale, shape))
        else:
            raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = _np.zeros(int(_np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)


class Load:
    """Init from a dict of arrays (parity initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k.replace("arg:", "").replace("aux:", ""): v for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(f"Parameter {name} cannot be initialized from loading: "
                                 f"shape mismatch {src.shape} vs {arr.shape}")
            arr[:] = src.asnumpy() if hasattr(src, "asnumpy") else src
        else:
            if self.default_init is None:
                raise MXNetError(f"Cannot Initialize parameter {name}: not found in loaded params")
            self.default_init(name, arr)


class Mixed:
    """Pattern-dispatched initializer list (parity initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have the same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"Parameter name {name} did not match any pattern")


_register_aliases()


# factory face: preserves get()'s contract (instance | name | None →
# Uniform default, 'zeros'/'ones' aliases, positional ctor args) and adds
# the generic registry.py JSON '[name, kwargs]' spec form
def create(*args, **kwargs):
    if args and (args[0] is None or isinstance(args[0], Initializer) or
                 (isinstance(args[0], str) and not args[0].startswith("["))):
        return get(args[0], *args[1:], **kwargs)
    from .registry import get_create_func

    return get_create_func(Initializer, "initializer")(*args, **kwargs)
