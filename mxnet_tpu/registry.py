"""Generic object-registry helpers (parity: `python/mxnet/registry.py` —
the create/register/alias machinery behind initializer/optimizer/lr-
scheduler string construction, e.g. `mx.init.create('xavier')`)."""
from __future__ import annotations

import json

from .base import MXNetError

_REGISTRIES = {}

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]


def _registry(base_class, nickname):
    return _REGISTRIES.setdefault((base_class, nickname), {})


def get_register_func(base_class, nickname):
    """Returns register(klass, name=None) for `base_class` objects."""
    reg = _registry(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            f"Can only register subclass of {base_class.__name__}"
        nm = (name or klass.__name__).lower()
        reg[nm] = klass
        return klass

    register.__doc__ = f"Register {nickname} to the {nickname} factory"
    return register


def get_alias_func(base_class, nickname):
    """Returns alias(*names) decorator registering extra names."""
    reg = _registry(base_class, nickname)

    def alias(*aliases):
        def deco(klass):
            for a in aliases:
                reg[a.lower()] = klass
            return klass
        return deco

    return alias


def get_create_func(base_class, nickname):
    """Returns create(spec, *args, **kwargs): spec may be an instance, a
    registered name, or the reference's json '[name, kwargs]' form."""
    reg = _registry(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            assert len(args) == 1 and not kwargs
            return args[0]
        if not args:
            raise MXNetError(f"{nickname} name is required")
        name, args = args[0], args[1:]
        if isinstance(name, str) and name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
        nm = str(name).lower()
        if nm not in reg:
            raise MXNetError(
                f"Cannot find {nickname} {name}. Registered: "
                f"{sorted(reg)}")
        return reg[nm](*args, **kwargs)

    create.__doc__ = f"Create a {nickname} instance from config"
    return create


# NOTE on scope (matching the reference): initializer builds its factory on
# this module; Optimizer.opt_registry (optimizer/optimizer.py:46 parity) and
# metric.create keep their own self-contained registries exactly as the
# reference's do — that is reference behavior, not drift.
