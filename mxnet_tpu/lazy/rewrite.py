"""Graph-rewrite optimizer for captured lazy segments.

The lazy engine (graph.py) compiles the recorded dataflow segment exactly
as captured; this module is the pass that *rewrites* that graph first —
the TVM rule-driven operator-fusion idea (arXiv:1802.04799) applied to
the segment the compile-once discipline (arXiv:2603.09555) already
amortizes: every rewrite is paid once per distinct segment signature and
replayed for free on every warm flush.

Pipeline position: AFTER weakref-liveness DCE and the stable renumbering
(the rewriter consumes the renumbered ``(specs, leaf_avals, out_spec)``
signature, never raw nodes), BEFORE the jitted flush compile. A rewritten
segment enters ``CompileCache("lazy")`` under a ``("rw", ...)`` key built
from the POST-rewrite signature plus the rule configuration, so rewritten
and unrewritten programs can never collide — and a config flip (per-rule
gate, spmd mesh change) keys a fresh executable instead of silently
reusing a stale one.

Three rule families, each individually disableable via
``MXNET_LAZY_REWRITE_DISABLE`` (comma-separated rule names):

* algebraic/fusion — ``identity`` (add-of-zeros / mul-by-one /
  double-negation / transpose-of-transpose / identity-op elimination),
  ``cse`` (dedup of identical (op, attrs, inputs) nodes),
  ``dense_bias_act`` (dot + bias-add + relu collapse — the fused op
  re-invokes the SAME registered fns, so the trace is bit-identical),
  ``conv_bn_relu`` (Convolution + eval-mode BatchNorm (+ relu) into the
  serving fusion kernel ``_fused_conv_bn_relu`` — generalizes the
  symbol-level ``TPU_FUSE`` pass to every lazy region; BN folding
  reorders float math, so parity is ulp-level, the PR 6 FMA precedent),
  ``map_reduce`` (a dead unary elementwise chain feeding a reduction
  merges into one ``_rw_map_reduce`` node).
* sharding-aware — ``spmd_constraint``: when ``MXNET_SPMD`` is gated,
  inject ``sharding_constraint`` nodes at large segment leaves using the
  PR 14 planner's residency mode (shape-only — lazy leaves are
  anonymous), so imperative op-by-op code inherits the 1/N layouts the
  fused step already gets. On a trivial (single-device / tp=1) mesh the
  constraint is a pure layout annotation and lowers to ZERO collectives
  (pinned by test_lazy_rewrite + the hlolint ``lazy`` contract row).
* bench-in-the-loop tuning lives in ``tools/lazy_tune.py`` (bench.py is
  the cost oracle; this module only honors the knobs it sweeps).

Vjp nodes are never rewritten (their residual pytree structure is pinned
by ``_LazyVjp``); they only *consume* rewritten forward values, which is
how autograd captured inside a segment sees the rewritten forward.

The rewrite PLAN is memoized per (pre-rewrite signature, config token):
a steady-state flush pays one dict hit, preserving the lazy lane's
host-dispatch win. Rule metadata lives in :data:`RULES` — the one
registry the symbol-level fusion pass (symbol/fusion.py) shares via
:func:`fused_conv_bn_attrs`.
"""
from __future__ import annotations

import collections
import functools

from .. import analysis
from .. import telemetry

__all__ = ["enabled", "disabled_rules", "plan_for", "note_applied",
           "RULES", "rule_names", "fused_conv_bn_attrs", "config_token"]


# ---------------------------------------------------------------------------
# rule registry — shared metadata for the lazy rewriter AND the symbol-level
# fusion pass (symbol/fusion.py tags its TPU_FUSE property as the "symbol"
# implementation of conv_bn_relu; docs/faq/env_var.md lists these names as
# the MXNET_LAZY_REWRITE_DISABLE vocabulary)
# ---------------------------------------------------------------------------

class Rule:
    __slots__ = ("name", "family", "doc", "levels", "parity")

    def __init__(self, name, family, doc, levels=("lazy",), parity="bit"):
        self.name = name
        self.family = family
        self.doc = doc
        self.levels = tuple(levels)   # where implementations exist
        self.parity = parity          # "bit" | "ulp" vs the unrewritten replay


RULES = collections.OrderedDict()


def _rule(name, family, doc, levels=("lazy",), parity="bit"):
    RULES[name] = Rule(name, family, doc, levels, parity)


_rule("identity", "algebraic",
      "drop add-of-_zeros / mul-by-_ones / sub-of-_zeros nodes (shape and "
      "dtype proven equal from avals), scalar +0/*1/div-1, double "
      "negation, transpose-of-transpose composing to the identity "
      "permutation, and the identity op")
_rule("cse", "algebraic",
      "merge nodes with identical (op, attrs, kind='op', inputs); "
      "duplicated LIVE outputs collapse to one program output")
_rule("dense_bias_act", "fusion",
      "dot -> (broadcast|elemwise)_add bias -> relu/Activation(relu) "
      "collapses to _rw_dense_bias_act (re-invokes the same registered "
      "fns: bit-identical trace, fewer segment nodes)")
_rule("conv_bn_relu", "fusion",
      "Convolution -> eval-mode BatchNorm (-> relu) folds into "
      "_fused_conv_bn_relu — the lazy-level generalization of the "
      "symbol-level TPU_FUSE pass (symbol/fusion.py shares "
      "fused_conv_bn_attrs)", levels=("lazy", "symbol"), parity="ulp")
_rule("map_reduce", "fusion",
      "a dead unary elementwise chain (>= 2 links) feeding sum/mean/max/"
      "min merges into one _rw_map_reduce node (same fns, same trace)")
_rule("spmd_constraint", "sharding",
      "inject _rw_sharding_constraint at large leaves per the spmd "
      "residency plan (shape-only infer_param_sharding); trivial meshes "
      "get replicated annotations that lower to zero collectives")


def rule_names():
    return tuple(RULES)


def fused_conv_bn_attrs(conv_attrs, bn_attrs, with_relu):
    """The `_fused_conv_bn_relu` attr dict from a Convolution + BatchNorm
    attr pair — the ONE place the conv+bn fold's parameters are assembled;
    both the lazy rule here and symbol/fusion.py's TPU_FUSE property call
    it, so the two levels can never drift."""
    attrs = {k: v for k, v in dict(conv_attrs).items()
             if k in ("kernel", "stride", "dilate", "pad", "num_filter",
                      "num_group", "layout")}
    bn = dict(bn_attrs)
    attrs["eps"] = bn.get("eps", 1e-3)
    attrs["fix_gamma"] = bn.get("fix_gamma", True)
    attrs["with_relu"] = bool(with_relu)
    return attrs


# ---------------------------------------------------------------------------
# gates (env knobs memoized on the raw string — the graph.py pattern)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _parse_enabled(raw):
    return raw not in ("0", "false", "False")


def enabled():
    """MXNET_LAZY_REWRITE — default ON (active only inside a lazy flush,
    so MXNET_LAZY still gates everything)."""
    import os

    raw = os.environ.get("MXNET_LAZY_REWRITE")
    return raw is None or _parse_enabled(raw)


@functools.lru_cache(maxsize=32)
def _parse_disabled(raw):
    names = frozenset(s.strip() for s in (raw or "").split(",") if s.strip())
    unknown = names - frozenset(RULES)
    if unknown:
        # loud, once per distinct value: a typo here silently re-enables
        telemetry.counter("lazy.rewrite.unknown_disable_names").inc()
    return names


def disabled_rules():
    """MXNET_LAZY_REWRITE_DISABLE as a frozenset of rule names."""
    import os

    return _parse_disabled(os.environ.get("MXNET_LAZY_REWRITE_DISABLE"))


def config_token():
    """Hashable token of everything that can change the rewrite output
    for a fixed input signature: the disabled-rule set and (when the
    sharding rule is live) the spmd mesh + size floor. Part of the
    rewritten cache key — a mesh or gate flip compiles fresh."""
    dis = disabled_rules()
    spmd_token = None
    if "spmd_constraint" not in dis:
        import os

        if str(os.environ.get("MXNET_SPMD") or "").strip():
            try:
                from ..base import getenv
                from ..parallel import spmd as _spmd

                spmd_token = (_spmd.spmd_mesh(),
                              int(getenv("MXNET_SPMD_FSDP_MIN_SIZE")))
            except Exception:  # noqa: BLE001 — unsatisfiable spec: no rule
                spmd_token = None
    return (dis, spmd_token)


# ---------------------------------------------------------------------------
# IR — a tiny mutable view over the renumbered segment specs.
# refs: ("n",) | ("l", leaf_idx) | (_RNode, out_idx)
# ---------------------------------------------------------------------------

class _RNode:
    __slots__ = ("op_name", "frozen", "kind", "ins", "n_flat")

    def __init__(self, op_name, frozen, kind, ins, n_flat):
        self.op_name = op_name
        self.frozen = frozen      # hashable attr tuple (registry._freeze)
        self.kind = kind          # 'op' | 'vjp'
        self.ins = list(ins)
        self.n_flat = n_flat

    def attrs(self):
        return dict(self.frozen)


def _parse(specs, out_spec):
    nodes = []
    for op_name, frozen, kind, ins, n_flat in specs:
        rins = []
        for r in ins:
            if r == ("n",):
                rins.append(("n",))
            elif r[0] == "l":
                rins.append(("l", r[1]))
            else:  # ("s", (k, i))
                k, i = r[1]
                rins.append((nodes[k], i))
        nodes.append(_RNode(op_name, frozen, kind, rins, n_flat))
    outs = [(nodes[k], i) for (k, i) in out_spec]
    return nodes, outs


def _is_node_ref(r):
    return isinstance(r[0], _RNode)


def _apply_sub(nodes, outs, sub):
    """Rewrite every input/output ref through the substitution map
    (chains resolve transitively; subs only ever point backward in topo
    order, so no cycles)."""
    if not sub:
        return

    def res(r):
        while _is_node_ref(r):
            nxt = sub.get((r[0], r[1]))
            if nxt is None:
                return r
            r = nxt
        return r

    for n in nodes:
        n.ins = [r if not _is_node_ref(r) else res(r) for r in n.ins]
    outs[:] = [r if not _is_node_ref(r) else res(r) for r in outs]


def _uses(nodes, outs):
    """(use-count per slot, set of slots that are live outputs, consumer
    map slot -> [nodes])."""
    uses = collections.Counter()
    consumers = collections.defaultdict(list)
    for n in nodes:
        for r in n.ins:
            if _is_node_ref(r):
                uses[(r[0], r[1])] += 1
                consumers[(r[0], r[1])].append(n)
    out_slots = set()
    for r in outs:
        if _is_node_ref(r):
            uses[(r[0], r[1])] += 1
            out_slots.add((r[0], r[1]))
    return uses, out_slots, consumers


def _prune(nodes, outs):
    """Drop nodes no longer reachable from the live outputs — run after
    every pass so a substituted-away consumer stops inflating the use
    counts the fusion patterns key on."""
    reach = set()
    stack = [r[0] for r in outs if _is_node_ref(r)]
    while stack:
        n = stack.pop()
        if n in reach:
            continue
        reach.add(n)
        for r in n.ins:
            if _is_node_ref(r):
                stack.append(r[0])
    nodes[:] = [n for n in nodes if n in reach]


def _compute_avals(nodes, leaf_avals):
    """(shape, dtype) per (node, flat-out-idx), from the SAME cached
    abstract eval the recorder used — every key is a cache hit, so this
    pass is near-free on the plan-computation (cold) path. A node that
    cannot be abstractly evaluated simply has no entry (shape-checked
    rules skip it)."""
    from .graph import _abstract_eval

    avals = {}
    for n in nodes:
        in_sig = []
        ok = True
        for r in n.ins:
            if r == ("n",):
                in_sig.append(None)
            elif r[0] == "l":
                in_sig.append(leaf_avals[r[1]])
            else:
                a = avals.get((r[0], r[1]))
                if a is None:
                    ok = False
                    break
                in_sig.append(a)
        if not ok:
            continue
        try:
            ae = _abstract_eval(n.op_name, n.frozen, tuple(in_sig),
                                n.kind == "vjp")
        except Exception:  # noqa: BLE001 — no aval, shape rules skip
            ae = None
        if ae is None:
            continue
        out_avals, _single, _td, p_avals = ae
        flat = tuple(out_avals) + tuple(p_avals)
        if len(flat) != n.n_flat:
            continue
        for i, a in enumerate(flat):
            avals[(n, i)] = a
    return avals


# ---------------------------------------------------------------------------
# rule implementations — each returns the number of applications and
# mutates (nodes, outs) + a substitution map applied by the driver
# ---------------------------------------------------------------------------

_ADD_OPS = frozenset({"elemwise_add", "broadcast_add"})
_SUB_OPS = frozenset({"elemwise_sub", "broadcast_sub"})
_MUL_OPS = frozenset({"elemwise_mul", "broadcast_mul"})
_ZERO_OPS = frozenset({"_zeros", "zeros_like"})
_ONE_OPS = frozenset({"_ones", "ones_like"})

# unary links safe for the map_reduce chain merge: pure elementwise,
# attr-free, single-output (the fused node re-invokes the same fns)
_MR_UNARY = frozenset({
    "exp", "log", "log1p", "expm1", "sqrt", "rsqrt", "square", "abs",
    "tanh", "sigmoid", "relu", "negative", "erf", "sin", "cos",
})
_MR_REDUCE = frozenset({"sum", "mean", "max", "min"})


def _is_relu_like(n):
    if n.kind != "op":
        return False
    if n.op_name == "relu":
        return True
    return n.op_name == "Activation" and \
        str(n.attrs().get("act_type", "relu")) == "relu"


def _producer(r):
    """The producing op-kind node of a ref, or None."""
    if _is_node_ref(r) and r[0].kind == "op":
        return r[0]
    return None


def _pass_identity(nodes, outs, leaf_avals, avals):
    count = 0
    sub = {}

    def res(r):
        while _is_node_ref(r):
            nxt = sub.get((r[0], r[1]))
            if nxt is None:
                return r
            r = nxt
        return r

    def aval(r):
        if r == ("n",):
            return None
        if r[0] == "l":
            return leaf_avals[r[1]]
        return avals.get((r[0], r[1]))

    def norm_axes(n, ndim):
        ax = n.attrs().get("axes")
        if ax in (None, (), ""):
            return tuple(reversed(range(ndim)))
        return tuple(int(a) % ndim for a in ax)

    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n.kind != "op" or n.n_flat != 1 or (n, 0) in sub:
                continue
            out_a = avals.get((n, 0))
            rep = None
            ins = [res(r) for r in n.ins]
            if n.op_name in _ADD_OPS and len(ins) == 2:
                a, b = ins
                pa, pb = _producer(a), _producer(b)
                if pb is not None and pb.op_name in _ZERO_OPS \
                        and out_a is not None and out_a == aval(a):
                    rep = a
                elif pa is not None and pa.op_name in _ZERO_OPS \
                        and out_a is not None and out_a == aval(b):
                    rep = b
            elif n.op_name in _SUB_OPS and len(ins) == 2:
                a, b = ins
                pb = _producer(b)
                if pb is not None and pb.op_name in _ZERO_OPS \
                        and out_a is not None and out_a == aval(a):
                    rep = a
            elif n.op_name in _MUL_OPS and len(ins) == 2:
                a, b = ins
                pa, pb = _producer(a), _producer(b)
                if pb is not None and pb.op_name in _ONE_OPS \
                        and out_a is not None and out_a == aval(a):
                    rep = a
                elif pa is not None and pa.op_name in _ONE_OPS \
                        and out_a is not None and out_a == aval(b):
                    rep = b
            elif n.op_name in ("_plus_scalar", "_minus_scalar") and ins:
                if float(n.attrs().get("scalar", 0.0)) == 0.0:
                    rep = ins[0]
            elif n.op_name in ("_mul_scalar", "_div_scalar") and ins:
                if float(n.attrs().get("scalar", 0.0)) == 1.0:
                    rep = ins[0]
            elif n.op_name == "negative" and ins:
                p = _producer(ins[0])
                if p is not None and p.op_name == "negative" \
                        and ins[0][1] == 0:
                    rep = res(p.ins[0])
            elif n.op_name == "transpose" and ins:
                p = _producer(ins[0])
                a = out_a
                if p is not None and p.op_name == "transpose" \
                        and ins[0][1] == 0 and a is not None:
                    ndim = len(a[0])
                    p1 = norm_axes(p, ndim)
                    p2 = norm_axes(n, ndim)
                    if tuple(p1[p2[i]] for i in range(ndim)) \
                            == tuple(range(ndim)):
                        rep = res(p.ins[0])
            elif n.op_name == "identity" and ins:
                rep = ins[0]
            if rep is not None:
                sub[(n, 0)] = rep
                count += 1
                changed = True
    _apply_sub(nodes, outs, sub)
    return count


def _pass_cse(nodes, outs):
    count = 0
    sub = {}
    idx = {n: i for i, n in enumerate(nodes)}
    seen = {}

    def res(r):
        while _is_node_ref(r):
            nxt = sub.get((r[0], r[1]))
            if nxt is None:
                return r
            r = nxt
        return r

    for n in nodes:
        if n.kind != "op":
            continue
        key_ins = []
        for r in n.ins:
            r = res(r) if _is_node_ref(r) else r
            if _is_node_ref(r):
                key_ins.append(("s", idx[r[0]], r[1]))
            else:
                key_ins.append(r)
        key = (n.op_name, n.frozen, tuple(key_ins), n.n_flat)
        rep = seen.get(key)
        if rep is None:
            seen[key] = n
        else:
            for i in range(n.n_flat):
                sub[(n, i)] = (rep, i)
            count += 1
    _apply_sub(nodes, outs, sub)
    return count


def _pass_dense_bias_act(nodes, outs):
    from ..ops.registry import _freeze

    uses, out_slots, _cons = _uses(nodes, outs)
    sub = {}
    count = 0
    rebuilt = []
    for n in nodes:
        if _is_relu_like(n) and n.n_flat == 1 and n.ins:
            r_add = n.ins[0]
            add = _producer(r_add)
            if add is not None and r_add[1] == 0 \
                    and add.op_name in _ADD_OPS and add.n_flat == 1 \
                    and uses[(add, 0)] == 1 and (add, 0) not in out_slots \
                    and len(add.ins) == 2:
                dot_ref = bias_ref = None
                for cand, other in ((add.ins[0], add.ins[1]),
                                    (add.ins[1], add.ins[0])):
                    d = _producer(cand)
                    if d is not None and cand[1] == 0 \
                            and d.op_name == "dot" and d.n_flat == 1 \
                            and uses[(d, 0)] == 1 \
                            and (d, 0) not in out_slots \
                            and len(d.ins) == 2:
                        dot_ref, bias_ref = cand, other
                        break
                if dot_ref is not None:
                    d = dot_ref[0]
                    dat = d.attrs()
                    fused = _RNode(
                        "_rw_dense_bias_act",
                        _freeze({"transpose_a": dat.get("transpose_a", False),
                                 "transpose_b": dat.get("transpose_b", False),
                                 "act": "relu"}),
                        "op", [d.ins[0], d.ins[1], bias_ref], 1)
                    rebuilt.append(fused)
                    sub[(n, 0)] = (fused, 0)
                    count += 1
        rebuilt.append(n)
    nodes[:] = rebuilt
    _apply_sub(nodes, outs, sub)
    return count


def _pass_conv_bn_relu(nodes, outs):
    from ..ops._utils import parse_bool
    from ..ops.registry import _freeze

    uses, out_slots, consumers = _uses(nodes, outs)
    sub = {}
    count = 0
    inserts = {}  # target node -> [new nodes to place before it]
    fused_for = {}  # BN node -> (fused node, relu node or None)
    for b in nodes:
        if b.kind != "op" or b.op_name != "BatchNorm" or b.n_flat != 3 \
                or len(b.ins) != 5:
            continue
        battrs = b.attrs()
        if parse_bool(battrs.get("_train", False)):
            continue  # train-mode BN updates stats: fold is eval-only
        if int(battrs.get("axis", 1)) != 1:
            continue  # the fold scales weight dim 0 (NCHW channel axis)
        conv_ref = b.ins[0]
        c = _producer(conv_ref)
        if c is None or conv_ref[1] != 0 or c.op_name != "Convolution" \
                or uses[(c, 0)] != 1 or (c, 0) in out_slots:
            continue
        cattrs = c.attrs()
        if str(cattrs.get("layout", "NCHW")) != "NCHW":
            continue
        data, weight = c.ins[0], c.ins[1]
        new_nodes = []
        if len(c.ins) >= 3 and not parse_bool(cattrs.get("no_bias", False)):
            bias = c.ins[2]
        else:
            nf = int(cattrs.get("num_filter", 0))
            if nf <= 0:
                continue
            zero = _RNode("_zeros",
                          _freeze({"shape": (nf,), "dtype": "float32"}),
                          "op", [], 1)
            new_nodes.append(zero)
            bias = (zero, 0)
        # optional trailing relu: single consumer of the BN main output
        relu = None
        if uses[(b, 0)] == 1 and (b, 0) not in out_slots:
            cand = consumers[(b, 0)][0]
            if _is_relu_like(cand) and cand.n_flat == 1 \
                    and cand.ins and cand.ins[0] == (b, 0):
                relu = cand
        attrs = fused_conv_bn_attrs(cattrs, battrs, relu is not None)
        fused = _RNode("_fused_conv_bn_relu", _freeze(attrs), "op",
                       [data, weight, bias, b.ins[1], b.ins[2],
                        b.ins[3], b.ins[4]], 1)
        new_nodes.append(fused)
        target = relu if relu is not None else b
        inserts.setdefault(target, []).extend(new_nodes)
        fused_for[b] = (fused, relu)
        count += 1
    if count:
        rebuilt = []
        for n in nodes:
            rebuilt.extend(inserts.get(n, ()))
            rebuilt.append(n)
        nodes[:] = rebuilt
        for b, (fused, relu) in fused_for.items():
            if relu is not None:
                sub[(relu, 0)] = (fused, 0)
            else:
                sub[(b, 0)] = (fused, 0)
            # eval-mode BN passes the moving stats through untouched:
            # outputs 1/2 ARE inputs 3/4 (bit-exact), so live aux slots
            # and the frontend's mutate_aux writeback keep their values
            sub[(b, 1)] = b.ins[3]
            sub[(b, 2)] = b.ins[4]
        _apply_sub(nodes, outs, sub)
    return count


def _pass_map_reduce(nodes, outs):
    from ..ops.registry import _freeze

    uses, out_slots, _cons = _uses(nodes, outs)
    sub = {}
    count = 0
    rebuilt = []
    for n in nodes:
        if n.kind == "op" and n.op_name in _MR_REDUCE and n.n_flat == 1 \
                and len(n.ins) == 1 and (n, 0) not in sub:
            steps = []
            cur = n.ins[0]
            while True:
                p = _producer(cur)
                if p is None or cur[1] != 0 or p.n_flat != 1 \
                        or p.op_name not in _MR_UNARY or p.frozen != () \
                        or len(p.ins) != 1 or uses[(p, 0)] != 1 \
                        or (p, 0) in out_slots:
                    break
                steps.append(p.op_name)
                cur = p.ins[0]
            if len(steps) >= 2:
                fused = _RNode(
                    "_rw_map_reduce",
                    _freeze({"steps": ",".join(reversed(steps)),
                             "reduce_op": n.op_name,
                             "reduce_attrs": n.frozen}),
                    "op", [cur], 1)
                rebuilt.append(fused)
                sub[(n, 0)] = (fused, 0)
                count += 1
        rebuilt.append(n)
    nodes[:] = rebuilt
    _apply_sub(nodes, outs, sub)
    return count


def _pass_spmd_constraint(nodes, outs, leaf_avals, spmd_token):
    from ..ops.registry import _freeze
    from ..parallel.spmd import infer_param_sharding

    mesh, min_size = spmd_token
    used_leaves = set()
    for n in nodes:
        for r in n.ins:
            if not _is_node_ref(r) and r != ("n",) and r[0] == "l":
                used_leaves.add(r[1])
    cands = {}
    for j in sorted(used_leaves):
        shape = leaf_avals[j][0]
        size = 1
        for s in shape:
            size *= int(s)
        if size >= int(min_size) and shape:
            cands[j] = shape
    if not cands:
        return 0
    trivial = int(mesh.devices.size) == 1
    plan = infer_param_sharding(mesh, None, cands,
                                residency_axes=tuple(mesh.axis_names))
    count = 0
    front = []
    wires = {}  # leaf idx -> constraint node
    for j in sorted(cands):
        spec = tuple(plan.get(j, ()))
        if all(p is None for p in spec):
            if not trivial:
                continue  # replicated on a real mesh: annotation buys nothing
            spec = ()  # trivial mesh: a pure layout annotation (the tp=1
            #            zero-collectives pin in test_lazy_rewrite)
        node = _RNode("_rw_sharding_constraint",
                      _freeze({"mesh": mesh, "spec": spec}),
                      "op", [("l", j)], 1)
        front.append(node)
        wires[j] = node
        count += 1
    if count:
        injected = set(front)
        for n in nodes:
            if n in injected:
                continue
            n.ins = [(wires[r[1]], 0)
                     if (not _is_node_ref(r) and r != ("n",) and r[0] == "l"
                         and r[1] in wires) else r
                     for r in n.ins]
        nodes[:] = front + nodes
    return count


# ---------------------------------------------------------------------------
# linearize back into replay specs
# ---------------------------------------------------------------------------

def _linearize(nodes, outs, leaf_avals):
    reach = set()
    stack = [r[0] for r in outs if _is_node_ref(r)]
    while stack:
        n = stack.pop()
        if n in reach:
            continue
        reach.add(n)
        for r in n.ins:
            if _is_node_ref(r):
                stack.append(r[0])
    kept = [n for n in nodes if n in reach]

    leaf_sel, leaf_map = [], {}

    def lref(j):
        if j not in leaf_map:
            leaf_map[j] = len(leaf_sel)
            leaf_sel.append(j)
        return leaf_map[j]

    idx = {}
    specs = []
    for k, n in enumerate(kept):
        ins = []
        for r in n.ins:
            if r == ("n",):
                ins.append(("n",))
            elif not _is_node_ref(r):
                ins.append(("l", lref(r[1])))
            else:
                ins.append(("s", (idx[r[0]], r[1])))
        idx[n] = k
        specs.append((n.op_name, n.frozen, n.kind, tuple(ins), n.n_flat))
    out_spec = []
    for r in outs:
        if _is_node_ref(r):
            out_spec.append((idx[r[0]], r[1]))
        else:
            out_spec.append(("l", lref(r[1])))
    leaf_avals2 = tuple(leaf_avals[j] for j in leaf_sel)
    return tuple(specs), tuple(out_spec), tuple(leaf_sel), leaf_avals2


# ---------------------------------------------------------------------------
# plan memo — steady-state flushes pay one OrderedDict hit
# ---------------------------------------------------------------------------

class Plan:
    __slots__ = ("specs", "out_spec", "leaf_sel", "leaf_avals", "stats",
                 "cfg")

    def __init__(self, specs, out_spec, leaf_sel, leaf_avals, stats, cfg):
        self.specs = specs
        self.out_spec = out_spec
        self.leaf_sel = leaf_sel
        self.leaf_avals = leaf_avals
        self.stats = stats    # {"rules": ((name, n), ...), "nodes_pre": .,
        #                        "nodes_post": .}
        self.cfg = cfg

    def cache_key(self):
        """The POST-rewrite CompileCache('lazy') key: namespaced so a
        rewritten program can never collide with an unrewritten one, and
        carrying the config token so gate/mesh flips compile fresh."""
        return ("rw", self.cfg, self.specs, self.leaf_avals, self.out_spec)


_PLANS = collections.OrderedDict()
_PLANS_LOCK = analysis.make_lock("lazy.rewrite_plans")
_PLANS_BOUND = 512
_MISS = object()


def plan_for(sig):
    """Memoized rewrite plan for a renumbered segment signature, or None
    when no rule fires (the caller then uses the ORIGINAL signature and
    cache entry — rewrite-on and rewrite-off share executables for
    segments the rewriter leaves alone)."""
    cfg = config_token()
    key = (cfg, sig)
    with _PLANS_LOCK:
        hit = _PLANS.get(key, _MISS)
        if hit is not _MISS:
            _PLANS.move_to_end(key)
            return hit
    try:
        plan = _compute_plan(sig, cfg)
    except Exception:  # noqa: BLE001 — a planner bug must degrade to
        #               the unrewritten (always-correct) program
        telemetry.counter("lazy.rewrite.plan_errors").inc()
        plan = None
    with _PLANS_LOCK:
        _PLANS[key] = plan
        while len(_PLANS) > _PLANS_BOUND:
            _PLANS.popitem(last=False)
    return plan


def _compute_plan(sig, cfg):
    specs, leaf_avals, out_spec = sig
    dis, spmd_token = cfg
    if not specs:
        return None
    live = [r for r in rule_names() if r not in dis
            and (r != "spmd_constraint" or spmd_token is not None)]
    if not live:
        return None
    nodes, outs = _parse(specs, out_spec)
    avals = _compute_avals(nodes, leaf_avals)
    applied = []

    def run(name, fn, *args):
        if name in live:
            n = fn(*args)
            if n:
                applied.append((name, n))
                _prune(nodes, outs)

    run("identity", _pass_identity, nodes, outs, leaf_avals, avals)
    run("cse", _pass_cse, nodes, outs)
    run("dense_bias_act", _pass_dense_bias_act, nodes, outs)
    run("conv_bn_relu", _pass_conv_bn_relu, nodes, outs)
    run("map_reduce", _pass_map_reduce, nodes, outs)
    if spmd_token is not None:
        run("spmd_constraint", _pass_spmd_constraint, nodes, outs,
            leaf_avals, spmd_token)
    if not applied:
        return None
    specs2, out_spec2, leaf_sel, leaf_avals2 = \
        _linearize(nodes, outs, leaf_avals)
    stats = {"rules": tuple(applied), "nodes_pre": len(specs),
             "nodes_post": len(specs2)}
    return Plan(specs2, out_spec2, leaf_sel, leaf_avals2, stats, cfg)


def note_applied(plan):
    """Per-flush telemetry for a rewritten segment (counted every flush,
    not once per plan, so steady-state traffic shows up in rates;
    tools/telemetry_report.py renders the 'rewrite:' line and
    telemetry.snapshot() derives lazy.rewrite.shrink_ratio and the
    pre/post mean ops per rewritten segment)."""
    telemetry.counter("lazy.rewrite.segments").inc()
    for name, n in plan.stats["rules"]:
        telemetry.counter(f"lazy.rewrite.rules_applied.{name}").inc(n)
    pre = plan.stats["nodes_pre"]
    post = plan.stats["nodes_post"]
    telemetry.counter("lazy.rewrite.nodes_pre").inc(pre)
    telemetry.counter("lazy.rewrite.nodes_post").inc(post)
    if pre > post:
        telemetry.counter("lazy.rewrite.nodes_eliminated").inc(pre - post)
