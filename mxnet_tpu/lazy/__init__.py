"""Lazy eager execution — deferred dataflow capture for the op-by-op path.

``MXNET_LAZY=1`` turns every imperative NDArray op into a recorded node of
a per-thread dataflow segment instead of a one-op XLA dispatch; any
concrete-value escape (``asnumpy``/``item``/``print``, ``wait_to_read``,
bool/len on values, engine/kvstore/checkpoint handoffs, feeding a bound
executor) flushes the segment as ONE fused jitted program through the
named ``CompileCache("lazy")`` — see :mod:`mxnet_tpu.lazy.graph` for the
design and docs/faq/env_var.md (Lazy section) for the knobs. Default OFF:
per-op eager remains the bit-parity reference (test_lazy.py sweeps it).
"""
from __future__ import annotations

from ..base import register_env
from .graph import (LazyArray, LazyGraph, enabled, flush_all, force_list,
                    graph_for_thread, lazy_stats, pending_ops)

__all__ = ["LazyArray", "LazyGraph", "enabled", "flush_all", "force_list",
           "graph_for_thread", "lazy_stats", "pending_ops"]

register_env("MXNET_LAZY", False,
             "defer imperative NDArray ops into per-thread dataflow "
             "segments compiled as ONE fused XLA program per "
             "materialization barrier (default off; per-op eager is the "
             "bit-parity reference)")
register_env("MXNET_LAZY_REWRITE", 1,
             "graph-rewrite the captured segment before the flush compile "
             "(lazy/rewrite.py: identity elimination, CSE, dense/conv "
             "fusion, map-reduce merge, spmd constraint injection); "
             "active only under MXNET_LAZY; rewritten programs key the "
             "cache by their post-rewrite signature")
register_env("MXNET_LAZY_REWRITE_DISABLE", "",
             "comma-separated rewrite rule names to turn off individually "
             "(identity, cse, dense_bias_act, conv_bn_relu, map_reduce, "
             "spmd_constraint) while keeping the rest")
register_env("MXNET_LAZY_MAX_OPS", 256,
             "flush a lazy segment when it reaches this many recorded ops "
             "(bounds host memory and compile size)")
register_env("MXNET_LAZY_CACHE_SIZE", 256,
             "max compiled segment executables kept in CompileCache('lazy') "
             "(LRU eviction)")
register_env("MXNET_LAZY_CHURN_WINDOW", 32,
             "hysteresis window: number of recent segment flushes inspected "
             "for compile-cache churn")
register_env("MXNET_LAZY_CHURN_RATIO_PCT", 50,
             "hysteresis trip point: if more than this percentage of the "
             "window's flushes were cache misses, capture disables for the "
             "cool-off")
register_env("MXNET_LAZY_COOLOFF", 512,
             "ops to run per-op eager after a hysteresis trip before "
             "re-trying capture")
register_env("MXNET_OP_CACHE_SIZE", 1024,
             "max entries in each per-op eager jit cache "
             "(CompileCache('op_eager') / ('op_vjp'), LRU eviction)")

