"""Lazy dataflow graph — deferred op capture + fused-segment compilation.

This is the framework's rendering of the reference's L2 dependency engine
(`src/engine/threaded_engine.h`: every imperative op becomes a node in an
async dataflow graph; `WaitForVar` materializes): under ``MXNET_LAZY=1``
an eager NDArray op does NOT dispatch a one-op XLA program — it records a
node into a per-thread :class:`LazyGraph` (op + attrs, edges = which
earlier node/leaf produced each input; in-place writes are versioned for
free because an NDArray mutation swaps its buffer handle, so the old
value stays addressable by the nodes that read it — the ``ThreadedVar``
version bump, functionally). A **materialization barrier** — any read of
a concrete value (`asnumpy`/`item`/`print`, control flow on values,
`wait_to_read`, an engine/kvstore/checkpoint handoff, feeding a bound
executor) — flushes the pending graph as ONE jitted XLA program per
segment through the named ``CompileCache("lazy")``.

The segment cache key is the full dataflow signature: topologically
ordered (op, attrs) specs, the wiring between them, the shape/dtype of
every leaf, and which outputs are still live. A steady training or
inference loop therefore replays cached executables with ZERO
steady-state compiles (asserted by test_lazy.py), and XLA fuses across
the whole chain — the TVM elementwise/injective-chain grouping
(arXiv:1802.04799) delegated to the compiler, per the compile-once
discipline of arXiv:2603.09555.

Autograd composes: a recorded op captures ``jax.vjp`` INSIDE the segment
(forward + residuals in one program); the tape receives a
:class:`_LazyVjp` pullback whose first application materializes the
segment. Backward itself stays per-node ``run_vjp`` — identical math to
the eager tape.

Fallbacks (the per-op safety net):

* ops that cannot trace (``eager_only``, ``Custom`` host callbacks) run
  eagerly WITHOUT flushing the pending segment (pure values have no
  ordering hazard);
* a segment whose signature churns the cache (shape-polymorphic user
  code) trips a hysteresis: capture disables for a cool-off window and
  per-op eager — always the bit-parity reference — takes over;
* a trace/compile failure at flush falls back to per-op eager REPLAY of
  the same recorded nodes, so a lazy bug degrades to slow, never wrong.
"""
from __future__ import annotations

import collections
import functools
import threading
import weakref

import jax
import jax.numpy as jnp

from .. import analysis
from .. import health
from .. import telemetry
from .. import tracing
from ..base import MXNetError

__all__ = ["LazyArray", "LazyGraph", "enabled", "graph_for_thread",
           "force_list", "flush_all", "pending_ops", "lazy_stats"]

# ops that must never be captured: eager_only is flagged on the Op itself
# (data-dependent shapes); Custom runs user python through host callbacks
# whose side effects (their own nd ops, prints) must not happen inside a
# deferred replay.
_UNJITTABLE = frozenset({"Custom"})

_CACHE = None
_CACHE_LOCK = analysis.make_lock("lazy.segment_cache")


def _segment_cache():
    global _CACHE
    if _CACHE is None:
        with _CACHE_LOCK:
            if _CACHE is None:
                from ..base import getenv
                from ..compile_cache import CompileCache

                # track_memory=False: segment count scales with distinct
                # dataflow signatures (hundreds in a diverse process), and
                # the /memory scrape's per-entry AOT analysis would re-pay
                # a compile for each — same exclusion as the per-op caches
                _CACHE = CompileCache(
                    "lazy", maxsize=int(getenv("MXNET_LAZY_CACHE_SIZE", 256)),
                    track_memory=False)
    return _CACHE


# env knobs memoized on the raw string (read per record/flush, never
# re-parsed unless the variable actually changes — the tracing.py pattern)
@functools.lru_cache(maxsize=64)
def _int_env(name, raw, default):
    try:
        return int(raw) if raw not in (None, "") else default
    except ValueError:
        return default


def _knob(name, default):
    import os

    return _int_env(name, os.environ.get(name), default)


def enabled():
    """The MXNET_LAZY master gate — one dict lookup when off."""
    import os

    raw = os.environ.get("MXNET_LAZY")
    return raw not in (None, "", "0", "false", "False")


class LazyArray:
    """A pending (or realized) value: one flat output slot of one node of
    one :class:`LazyGraph`. Shape/dtype queries are free (abstract value);
    :meth:`force` is the materialization barrier."""

    __slots__ = ("graph", "slot", "gen", "_shape", "_dtype", "value",
                 "__weakref__")

    def __init__(self, graph, slot, gen, shape, dtype):
        self.graph = graph
        self.slot = slot
        self.gen = gen  # graph generation: stale after the owning flush
        self._shape = tuple(shape)
        self._dtype = dtype
        self.value = None  # set by the owning graph's flush

    # -- the duck-typed subset NDArray metadata queries need ----------------

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        n = 1
        for s in self._shape:
            n *= int(s)
        return n

    def force(self, reason="value"):
        """Materialize: flush the owning graph's pending segment (a no-op
        if some other barrier already flushed it) and return the concrete
        jax array."""
        v = self.value
        if v is None:
            self.graph.flush(reason)
            v = self.value
            if v is None:  # cannot happen: flush realizes every live slot
                raise MXNetError("lazy value was lost at flush — this is a "
                                 "bug in mxnet_tpu.lazy")
        return v

    def __repr__(self):
        state = "pending" if self.value is None else "realized"
        return f"LazyArray({state}, shape={self._shape}, dtype={self._dtype})"


def _lazy_pullback_base():
    from ..autograd import _PyPullback

    return _PyPullback


class _LazyVjp(_lazy_pullback_base()):
    """The tape-side pullback of a lazily captured op: holds the segment's
    residual slots (strong refs — the tape keeps residuals alive) and the
    pullback pytree structure from abstract eval. First application
    materializes the segment, rebuilds the ``tree_util.Partial`` and runs
    it through the shared jitted ``run_vjp`` — byte-for-byte the eager
    tape's backward convention."""

    def __init__(self, treedef, leaves):
        self.treedef = treedef
        self.leaves = list(leaves)   # LazyArray residuals (strong refs)
        self.value = None            # realized Partial (eager-replay sets it)
        super().__init__(self._run)

    def _partial(self):
        if self.value is None:
            concrete = [la.force("backward") for la in self.leaves]
            self.value = jax.tree_util.tree_unflatten(self.treedef, concrete)
        return self.value

    def _run(self, cts):
        from ..ops.registry import run_vjp

        return run_vjp(self._partial(), cts)


class _Node:
    __slots__ = ("op_name", "frozen", "in_slots", "base", "n_flat",
                 "out_refs", "kind", "n_out", "single", "vjp_ref")

    def __init__(self, op_name, frozen, in_slots, base, n_flat, kind,
                 n_out, single):
        self.op_name = op_name
        self.frozen = frozen          # _freeze()d wrapped attrs
        self.in_slots = in_slots      # tuple of ('l', i) | ('s', slot) | None
        self.base = base              # first flat output slot
        self.n_flat = n_flat          # total flat outputs (incl. residuals)
        self.kind = kind              # 'op' | 'vjp'
        self.n_out = n_out            # user-visible outputs (prefix)
        self.single = single          # fn returns a bare array, not a tuple
        self.out_refs = [None] * n_flat  # weakrefs to LazyArrays
        self.vjp_ref = None           # weakref to the _LazyVjp (kind='vjp')


@functools.lru_cache(maxsize=8192)
def _abstract_eval(op_name, frozen, in_sig, want_vjp):
    """Cached shape/dtype inference for one captured op: returns
    (out_avals tuple, single flag, partial_treedef, n_partial_leaves) or
    None when the op cannot be abstractly evaluated (memoized decline —
    the op then runs per-op eager forever). ``in_sig``: tuple of
    (shape, dtype) | None per input."""
    from ..ops.registry import _OPS

    op = _OPS[op_name]
    attrs = dict(frozen)

    def fn(*arrays):
        return op.fn(*arrays, **attrs)

    avals = [None if s is None else jax.ShapeDtypeStruct(s[0], s[1])
             for s in in_sig]
    try:
        if want_vjp:
            out, pvjp = jax.eval_shape(lambda *a: jax.vjp(fn, *a), *avals)
            p_leaves, p_treedef = jax.tree_util.tree_flatten(pvjp)
        else:
            out = jax.eval_shape(fn, *avals)
            p_leaves, p_treedef = (), None
        single = not isinstance(out, (tuple, list))
        outs = (out,) if single else tuple(out)
        out_avals = tuple((tuple(o.shape), o.dtype) for o in outs)
        if any(not hasattr(l, "shape") for l in p_leaves):
            return None  # a non-array residual leaf cannot cross the jit
        p_avals = tuple((tuple(l.shape), l.dtype) for l in p_leaves)
        return (out_avals, single, p_treedef, p_avals)
    except Exception:  # noqa: BLE001 — decline capture, eager is always right
        return None


class LazyGraph:
    """Per-thread pending dataflow segment + flush machinery."""

    def __init__(self):
        self._lock = analysis.make_rlock("lazy.graph")
        self._nodes = []
        self._leaves = []         # concrete jax arrays, deduped by id
        self._leaf_index = {}     # id(array) -> leaf idx
        self._n_slots = 0
        self._gen = 0             # bumped per flush (stale-slot guard)
        self._flushing = False
        # signature-churn hysteresis (the PR 3 pad/reshape model): when the
        # recent flush window is mostly cache misses, disable capture for a
        # cool-off and let per-op eager absorb the churn
        self._window = []
        self._ops_seen = 0
        self._cooloff_until = 0
        self._seen_sigs = collections.OrderedDict()
        self._beacon = None       # lazy: stall-watchdog flush beacon

    def _flush_beacon(self):
        """This graph's stall-watchdog beacon (created on first use —
        graphs are per-thread, so the thread id names it)."""
        if self._beacon is None:
            self._beacon = health.beacon(
                f"lazy.flush.{threading.get_ident()}", owner=self)
        return self._beacon

    # -- capture -------------------------------------------------------------

    def capture_allowed(self):
        self._ops_seen += 1
        if self._flushing:
            return False
        if self._ops_seen < self._cooloff_until:
            return False
        if self._cooloff_until and self._ops_seen >= self._cooloff_until:
            self._cooloff_until = 0
            self._window.clear()
        return True

    def _resolve(self, x):
        """Pre-lock input resolution: concrete values stay as-is; a
        pending value of ANOTHER thread's graph is forced here — BEFORE
        taking our own lock, so two graphs can never deadlock. Pending
        values of THIS graph pass through as LazyArrays and are classified
        under the lock (a peer thread may flush us in between — the
        generation check there handles it)."""
        if x is None:
            return None
        if isinstance(x, LazyArray):
            if x.value is not None:
                return x.value
            if x.graph is not self:
                return x.force()
        return x

    def record(self, op, arrays, attrs, want_vjp):
        """Try to capture one op invocation. Returns (outs, vjp) — outs a
        LazyArray or tuple of LazyArrays mirroring the eager return shape,
        vjp a _LazyVjp (or None when not recording) — or None to decline
        (caller runs the op per-op eager)."""
        if op.eager_only or op.name in _UNJITTABLE:
            telemetry.counter("lazy.fallback_ops").inc()
            return None
        if not self.capture_allowed():
            telemetry.counter("lazy.fallback_ops").inc()
            return None
        resolved = [self._resolve(a) for a in arrays]
        for r in resolved:
            if isinstance(r, jax.core.Tracer):
                return None  # being captured into an outer program
        if op.wrap_kwargs is not None:
            attrs = op.wrap_kwargs(dict(attrs))
        from ..ops.registry import _freeze

        try:
            frozen = _freeze(attrs)
            hash(frozen)
        except TypeError:
            telemetry.counter("lazy.fallback_ops").inc()
            return None
        # (shape, dtype) signature per input for abstract eval (pending
        # inputs carry their aval on the LazyArray — no graph walk)
        in_sig = tuple(
            None if r is None
            else (tuple(r.shape), jnp.result_type(r.dtype))
            for r in resolved)
        try:
            ae = _abstract_eval(op.name, frozen, in_sig, bool(want_vjp))
        except TypeError:  # unhashable attr slipped past _freeze
            ae = None
        if ae is None:
            telemetry.counter("lazy.fallback_ops").inc()
            return None
        out_avals, single, p_treedef, p_avals = ae

        with self._lock:
            in_slots = []
            for r in resolved:
                if r is None:
                    in_slots.append(None)
                elif isinstance(r, LazyArray):
                    if r.value is not None or r.gen != self._gen:
                        # a peer thread flushed us between resolution and
                        # the lock: the value is realized now — a leaf
                        in_slots.append(("l", self._leaf(r.force())))
                    else:
                        in_slots.append(("s", r.slot))
                else:
                    in_slots.append(("l", self._leaf(r)))
            n_out = len(out_avals)
            n_flat = n_out + len(p_avals)
            node = _Node(op.name, frozen, tuple(in_slots), self._n_slots,
                         n_flat, "vjp" if want_vjp else "op", n_out, single)
            self._n_slots += n_flat
            self._nodes.append(node)
            outs = []
            for i, (shp, dt) in enumerate(out_avals):
                la = LazyArray(self, node.base + i, self._gen, shp, dt)
                node.out_refs[i] = weakref.ref(la)
                outs.append(la)
            vjp = None
            if want_vjp:
                residuals = []
                for j, (shp, dt) in enumerate(p_avals):
                    la = LazyArray(self, node.base + n_out + j, self._gen,
                                   shp, dt)
                    node.out_refs[n_out + j] = weakref.ref(la)
                    residuals.append(la)
                vjp = _LazyVjp(p_treedef, residuals)
                node.vjp_ref = weakref.ref(vjp)
            telemetry.counter("lazy.ops_captured").inc()
            if health._enabled and len(self._nodes) == 1:
                # a segment is now pending: the stall watchdog counts
                # silence until the flush (no-flush-within-k×-median =
                # a barrier that never came)
                self._flush_beacon().arm()
            over_cap = len(self._nodes) >= _knob("MXNET_LAZY_MAX_OPS", 256)
        if over_cap:
            # bound host memory and compile size; the outputs just created
            # realize immediately (their NDArrays read concrete values)
            self.flush("segment_cap")
        return ((outs[0] if single else tuple(outs)), vjp)

    def _leaf(self, array):
        idx = self._leaf_index.get(id(array))
        if idx is None:
            idx = len(self._leaves)
            self._leaves.append(array)
            self._leaf_index[id(array)] = idx
        return idx

    # -- flush ---------------------------------------------------------------

    def flush(self, reason="value"):
        """Compile-and-run the pending segment as ONE fused XLA program and
        realize every live output. Safe to call with nothing pending."""
        with self._lock:
            if self._flushing or not self._nodes:
                return
            self._flushing = True
            nodes, leaves = self._nodes, self._leaves
            self._nodes, self._leaves = [], []
            self._leaf_index = {}
            self._n_slots = 0
            self._gen += 1
            try:
                if analysis._enabled:
                    # the flush compiles + runs a whole XLA program;
                    # holding any OTHER tracked lock across it is the
                    # PR 10 cross-graph deadlock class (the graph's own
                    # per-thread lock is the design, hence exempt)
                    analysis.check_blocking("lazy.flush",
                                            exempt=(self._lock,))
                self._flush_nodes(nodes, leaves, reason)
            finally:
                self._flushing = False
                if health._enabled:
                    # progress: the barrier fired (even an error-path
                    # flush replayed eagerly); nothing pending = idle
                    b = self._flush_beacon()
                    b.touch()
                    if not self._nodes:
                        b.idle()

    def _flush_nodes(self, nodes, leaves, reason):
        # liveness: a flat output slot is live iff its LazyArray is still
        # referenced (NDArray._buf or a tape _LazyVjp holds it strongly)
        live = {}
        for node in nodes:
            for i, ref in enumerate(node.out_refs):
                la = ref() if ref is not None else None
                if la is not None and la.value is None:
                    live[node.base + i] = la
        telemetry.counter("lazy.segments").inc()
        telemetry.counter(f"lazy.flush_reason.{reason}").inc()
        if not live:
            telemetry.histogram("lazy.segment_ops").record(0)
            return
        # dead-code elimination: keep only nodes a live slot depends on
        needed = set(live)
        kept = []
        for node in reversed(nodes):
            if any((node.base + i) in needed for i in range(node.n_flat)):
                kept.append(node)
                for s in node.in_slots:
                    if isinstance(s, tuple) and s[0] == "s":
                        needed.add(s[1])
        kept.reverse()
        telemetry.histogram("lazy.segment_ops").record(len(kept))

        # stable renumbering shared by the SIGNATURE and the REPLAY:
        # leaves in first-use order over the KEPT nodes, slots as
        # (kept-node index, flat output index). The replay must consume
        # these renumbered specs, never the nodes' original indices — DCE
        # can drop a node that introduced an earlier leaf, shifting every
        # later leaf position.
        leaf_order, leaf_renum = [], {}
        slot_renum = {}
        specs = []
        for k, node in enumerate(kept):
            ins = []
            for s in node.in_slots:
                if s is None:
                    ins.append(("n",))
                elif s[0] == "s":
                    ins.append(("s", slot_renum[s[1]]))
                else:
                    li = s[1]
                    if li not in leaf_renum:
                        leaf_renum[li] = len(leaf_order)
                        leaf_order.append(li)
                    ins.append(("l", leaf_renum[li]))
            for i in range(node.n_flat):
                slot_renum[node.base + i] = (k, i)
            specs.append((node.op_name, node.frozen, node.kind, tuple(ins),
                          node.n_flat))
        out_slots = sorted(live)
        out_spec = tuple(slot_renum[s] for s in out_slots)
        leaf_avals = tuple(
            (tuple(leaves[li].shape), jnp.result_type(leaves[li].dtype))
            for li in leaf_order)
        sig = (tuple(specs), leaf_avals, out_spec)

        cache = _segment_cache()
        hit = sig in self._seen_sigs
        if hit:
            self._seen_sigs.move_to_end(sig)
        else:
            self._seen_sigs[sig] = True
            bound = 4 * max(_knob("MXNET_LAZY_CHURN_WINDOW", 32), 8)
            while len(self._seen_sigs) > bound:
                self._seen_sigs.popitem(last=False)

        # graph rewrite (lazy/rewrite.py): pattern->replacement passes on
        # the renumbered signature, AFTER liveness DCE, BEFORE the compile.
        # The plan is memoized per (sig, config), so a warm flush pays one
        # dict hit; a rewritten segment keys the cache by its POST-rewrite
        # signature (plan.cache_key()) so rewritten and unrewritten
        # programs never collide. Churn hysteresis stays keyed on the
        # PRE-rewrite sig (capture-shape polymorphism is what it tracks).
        plan = None
        try:
            from . import rewrite as _rewrite

            if _rewrite.enabled():
                plan = _rewrite.plan_for(sig)
            if plan is not None:
                _rewrite.note_applied(plan)
        except Exception:  # noqa: BLE001 — a rewriter bug must degrade
            #               to the unrewritten (always-correct) program
            telemetry.counter("lazy.rewrite.plan_errors").inc()
            plan = None
        if plan is not None:
            key = plan.cache_key()
            r_specs, r_out = plan.specs, plan.out_spec
            args = [leaves[leaf_order[j]] for j in plan.leaf_sel]
        else:
            key, r_specs, r_out = sig, specs, out_spec
            args = [leaves[li] for li in leaf_order]

        def build():
            return jax.jit(_make_replay(r_specs, r_out))

        try:
            with tracing.span("lazy.flush", cat="lazy", reason=reason,
                              ops=len(kept), outputs=len(out_slots),
                              rewritten=plan is not None):
                fn = cache.get_or_build(key, build)
                outs = fn(*args)
        except Exception:  # noqa: BLE001 — degrade to slow, never wrong
            telemetry.counter("lazy.flush_errors").inc()
            if health._enabled:
                health.event("lazy_flush_error", ops=len(kept),
                             reason=reason)
            self._replay_eager(kept, leaves, live)
            self._churn(hit=False)
            return
        for la, v in zip((live[s] for s in out_slots), outs):
            la.value = v
        self._churn(hit)

    def _churn(self, hit):
        win = _knob("MXNET_LAZY_CHURN_WINDOW", 32)
        if win <= 0:
            return
        w = self._window
        w.append(0 if hit else 1)
        if len(w) > win:
            del w[:len(w) - win]
        if len(w) == win:
            pct = _knob("MXNET_LAZY_CHURN_RATIO_PCT", 50)
            if sum(w) * 100 > pct * win:
                # the segment signature keeps missing: user code is shape/
                # graph polymorphic here — stop paying capture + compile,
                # run per-op eager for a cool-off window
                self._cooloff_until = self._ops_seen + \
                    _knob("MXNET_LAZY_COOLOFF", 512)
                del w[:]
                telemetry.counter("lazy.hysteresis_trips").inc()
                if health._enabled:
                    health.event("lazy_hysteresis",
                                 cooloff_ops=_knob("MXNET_LAZY_COOLOFF",
                                                   512))

    def _replay_eager(self, kept, leaves, live):
        """Per-op eager replay of the recorded nodes — the fallback when
        the fused segment fails to trace or compile. Bit-identical to the
        pre-lazy eager path (same per-op jitted executables)."""
        from ..ops.registry import _jitted, _vjp_fwd_jitted

        env = {}

        def val(s):
            if s is None or s == ("n",):
                return None
            if s[0] == "l":
                return leaves[s[1]]
            return env[s[1]]

        for node in kept:
            ins = [val(s) for s in node.in_slots]
            if node.kind == "vjp":
                out, partial = _vjp_fwd_jitted(node.op_name, node.frozen)(*ins)
                vjp = node.vjp_ref() if node.vjp_ref is not None else None
                if vjp is not None:
                    vjp.value = partial
                outs = out if isinstance(out, tuple) else (out,)
                flat = list(outs)
                # residual slots: realized through the Partial (vjp.value);
                # fill any still-live residual LazyArray from its leaves so
                # force() never re-flushes
                p_leaves = jax.tree_util.tree_flatten(partial)[0]
                flat += list(p_leaves)
            else:
                out = _jitted(node.op_name, node.frozen, None)(*ins)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                flat = list(outs)
            for i, v in enumerate(flat):
                slot = node.base + i
                env[slot] = v
                la = live.get(slot)
                if la is not None:
                    la.value = v


def _make_replay(specs, out_spec):
    """Build the pure replay function from the RENUMBERED segment specs —
    the exact content the cache key hashes, so a cache hit built from a
    different (but sig-identical) graph replays the same computation.
    Inputs address leaves by their renumbered first-use position and
    producer outputs as (kept-node index, flat output index). Rewritten
    segments (lazy/rewrite.py) may additionally route an OUTPUT straight
    to a leaf — ("l", idx) — when identity elimination reduced it to a
    passthrough of an input."""
    from ..ops.registry import _OPS

    steps = []
    for op_name, frozen, kind, ins, n_flat in specs:
        steps.append((_OPS[op_name].fn, dict(frozen), kind, ins, n_flat))
    out_list = list(out_spec)

    def replay(*leaf_vals):
        env = {}

        def val(s):
            if s == ("n",):
                return None
            if s[0] == "l":
                return leaf_vals[s[1]]
            return env[s[1]]

        for k, (op_fn, attrs, kind, ins_spec, n_flat) in enumerate(steps):
            ins = [val(s) for s in ins_spec]
            if kind == "vjp":
                out, partial = jax.vjp(
                    lambda *a, _f=op_fn, _at=attrs: _f(*a, **_at), *ins)
                outs = out if isinstance(out, tuple) else (out,)
                flat = list(outs) + jax.tree_util.tree_flatten(partial)[0]
            else:
                out = op_fn(*ins, **attrs)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                flat = list(outs)
            if len(flat) != n_flat:
                raise MXNetError(
                    f"lazy replay of {op_fn}: {len(flat)} outputs, "
                    f"recorded {n_flat} (abstract/concrete trace mismatch)")
            for i, v in enumerate(flat):
                env[(k, i)] = v
        return tuple(leaf_vals[s[1]] if s[0] == "l" else env[s]
                     for s in out_list)

    return replay


# ---------------------------------------------------------------------------
# per-thread graphs
# ---------------------------------------------------------------------------

_tls = threading.local()
_graphs = weakref.WeakSet()
_graphs_lock = analysis.make_lock("lazy.graphs")


def graph_for_thread():
    g = getattr(_tls, "graph", None)
    if g is None:
        g = _tls.graph = LazyGraph()
        with _graphs_lock:
            _graphs.add(g)
    return g


def force_list(values, reason="value"):
    """Materialize every LazyArray in ``values`` (per-op eager fallback
    path: the op runs on concrete arrays)."""
    return [v.force(reason) if isinstance(v, LazyArray) else v
            for v in values]


def flush_all(reason="wait"):
    """Flush every thread's pending segment (``nd.waitall`` semantics: all
    outstanding work, not just this thread's, must be complete)."""
    with _graphs_lock:
        graphs = list(_graphs)
    for g in graphs:
        g.flush(reason)


def pending_ops():
    """Number of ops pending in the CURRENT thread's segment (tests)."""
    g = getattr(_tls, "graph", None)
    return len(g._nodes) if g is not None else 0


def lazy_stats():
    """{segments, ops_captured, fallback_ops, hysteresis_trips} from the
    telemetry counters plus the ``"lazy"`` compile-cache named totals —
    one stop for the bench lane and tests."""
    from ..compile_cache import named_stats

    snap = telemetry.snapshot()["counters"]
    out = {k.split("lazy.", 1)[1]: v for k, v in snap.items()
           if k.startswith("lazy.") and not k.startswith("lazy.flush_reason")}
    out["flush_reasons"] = {k.split("lazy.flush_reason.", 1)[1]: v
                            for k, v in snap.items()
                            if k.startswith("lazy.flush_reason.")}
    out["cache"] = named_stats("lazy")
    return out
