"""KVStore — key-value parameter synchronization.

Parity: `python/mxnet/kvstore.py` + `src/kvstore/kvstore_local.h:69` (local
reduce/broadcast across devices via `CommCPU/CommDevice`, `comm.h:103,451`)
and the factory `src/kvstore/kvstore.cc:40-77`.

TPU-native design: 'local'/'device' reduce across per-context replicas with
XLA ops (`add_n` — one fused reduction program per key group; the reference's
CommDevice merge-buffer trees are XLA's problem now). The 'dist_tpu_sync'
type (see `mxnet_tpu.parallel`) replaces the entire ps-lite worker/server
stack (`kvstore_dist.h:44`, `kvstore_dist_server.h:155`) with jax process
groups + AllReduce over ICI/DCN — push is a reduce-scatter fused into the
step, pull an all-gather; there are no server processes (SURVEY.md §5).
"""
from __future__ import annotations

import pickle
import time as _time

from . import telemetry
from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "KVStoreLocal", "create"]


def _nd_nbytes(v):
    """Logical byte size of one pushed/pulled value (telemetry)."""
    import numpy as _np

    try:
        return int(v.size) * _np.dtype(v.dtype).itemsize
    except Exception:  # noqa: BLE001 — telemetry must never break the push
        return 0


def _ctx_group_sum(arrays):
    """Sum a list of same-shape NDArrays living on (possibly) different
    contexts; result on the first array's context."""
    pivot = arrays[0]
    if len(arrays) == 1:
        return pivot.copy()
    moved = [a.as_in_context(pivot.context) for a in arrays]
    return nd.add_n(*moved)


class KVStoreBase:
    """Shared interface (parity `include/mxnet/kvstore.h:59`)."""

    def __init__(self):
        from .gradient_compression import GradientCompression
        self._updater = None
        self._updater_func = None
        self._gc = GradientCompression()

    # -- type/rank ----------------------------------------------------------

    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error-feedback residual
        (reference `gradient_compression.cc:45`): subsequent pushes are
        quantized to {-threshold, 0, +threshold}; init bypasses it."""
        self._gc.set_params(compression_params)

    def set_optimizer(self, optimizer):
        """Register optimizer so updates run 'on the kvstore' (parity
        kvstore.py set_optimizer; reference runs it on the server,
        `kvstore_dist_server.h:346` ApplyUpdates)."""
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        # a real error, not an assert: under `python -O` a bare assert
        # vanishes and this would write corrupt (None) state instead
        if self._updater is None:
            raise MXNetError("cannot save optimizer states: no updater set "
                             "(call set_optimizer first)")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("cannot load optimizer states: no updater set "
                             "(call set_optimizer first)")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def barrier(self):
        pass

    def _barrier(self):
        pass

    # -- gradient-sync bucket primitive (parallel/grad_sync.py) --------------

    def allreduce_flat(self, value, priority=0):
        """Sum one flat gradient bucket across device replicas and worker
        processes WITHOUT touching the store or the updater — the
        collective behind `GradSync` (one call per bucket instead of one
        push+pull per key). ``value`` is an NDArray or a list of per-device
        NDArrays; returns the reduced NDArray (dispatch is async — callers
        block via `GradSync.drain`)."""
        raise NotImplementedError

    def reduce_scatter_flat(self, value, num_shards, shard_index,
                            priority=0):
        """The ZeRO-1 sibling of :meth:`allreduce_flat`: reduce one flat
        bucket across replicas/workers but hand back only shard
        ``shard_index`` of ``num_shards`` equal slices (the bucket length
        must be divisible — pad with `parallel.pad_to_shards` first).
        A native ring ReduceScatter is HALF the allreduce bytes ((N-1)/N·B
        vs 2(N-1)/N·B), but the shipped eager implementations all reduce
        the full bucket and slice locally — the wire saving is realized
        only on the traced path (zero1.py's psum + sharding constraint,
        lowered by XLA). Returns the reduced shard NDArray."""
        raise NotImplementedError

    @property
    def fused_step_compatible(self):
        """Whether `Module.fused_step` may trace this store's gradient sync
        into the jitted train step instead of falling back to eager (see
        `fused_grad_sync_fn`)."""
        return False

    def fused_grad_sync_fn(self, entries):
        """A traceable ``grads_tuple -> grads_tuple`` cross-replica
        gradient sync for `Executor.fused_step`, or None when the sync is
        the identity (nothing to trace). ``entries`` =
        [(shape, dtype, priority), ...] aligned with the traced grads."""
        return None


class KVStoreLocal(KVStoreBase):
    """Single-process multi-device store (parity `kvstore_local.h:69`)."""

    def __init__(self, device=False):
        super().__init__()
        self._device = device
        self._store = {}       # key -> NDArray (the authoritative value)
        self._str_keys = False

    @property
    def type(self):
        return "device" if self._device else "local"

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _normalize(key, value):
        """Accept single key/value or lists; value may be a list of
        per-device NDArrays per key (grouped)."""
        if isinstance(key, (str, int)):
            key = [key]
            value = [value]
        # grouped calls must align exactly — a silent zip truncation would
        # drop the tail keys of a bucketed push without any error (a real
        # error, not an assert: `python -O` would strip the check)
        if len(key) != len(value):
            raise MXNetError(
                f"grouped call: {len(key)} keys but {len(value)} values")
        out = []
        for k, v in zip(key, value):
            if isinstance(v, NDArray):
                v = [v]
            out.append((k, list(v)))
        return out

    # -- API ----------------------------------------------------------------

    def init(self, key, value):
        """Initialize key-value pairs (parity kvstore.py:140)."""
        for k, vals in self._normalize(key, value):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            self._store[k] = vals[0].copy()

    def push(self, key, value, priority=0, ignore_sparse=True):
        """Reduce values across devices into the store; if an optimizer is
        registered (update_on_kvstore), apply the update immediately
        (parity kvstore.py:160; reference PushImpl `kvstore_local.h:121`)."""
        tele = telemetry._enabled
        t0 = _time.perf_counter() if tele else 0.0
        for k, vals in self._normalize(key, value):
            if tele:
                telemetry.counter("kvstore.push_bytes").inc(
                    sum(_nd_nbytes(v) for v in vals))
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized (call init first)")
            merged = _ctx_group_sum(vals)
            if self._gc.active:
                packed = self._gc.quantize(k, merged._data)
                merged = NDArray(self._gc.dequantize(
                    packed, merged.shape, merged.dtype), merged.context)
            if self._updater is not None:
                idx = k if isinstance(k, int) else _str_key_int(k)
                weight = self._store[k]
                merged = merged.as_in_context(weight.context)
                self._updater(idx, merged, weight)
            else:
                self._store[k] = merged.as_in_context(self._store[k].context)
        if tele:
            telemetry.histogram("kvstore.push_us").record(
                (_time.perf_counter() - t0) * 1e6)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast store values into out arrays (parity kvstore.py:240)."""
        assert out is not None
        tele = telemetry._enabled
        t0 = _time.perf_counter() if tele else 0.0
        for k, outs in self._normalize(key, out):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized (call init first)")
            src = self._store[k]
            if tele:
                telemetry.counter("kvstore.pull_bytes").inc(
                    sum(_nd_nbytes(o) for o in outs))
            for o in outs:
                o[:] = src.as_in_context(o.context)
        if tele:
            telemetry.histogram("kvstore.pull_us").record(
                (_time.perf_counter() - t0) * 1e6)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (allreduce semantics)."""
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def allreduce_flat(self, value, priority=0):
        """One bucket collective: reduce the per-device flat buffers with
        the same XLA `add_n` the per-key path uses — but across the whole
        bucket at once (`comm.h:451`'s role, one program per bucket)."""
        vals = value if isinstance(value, (list, tuple)) else [value]
        vals = [v if isinstance(v, NDArray) else NDArray(v) for v in vals]
        if telemetry._enabled:
            telemetry.counter("kvstore.bucket_collectives").inc()
            telemetry.counter("kvstore.bucket_bytes").inc(_nd_nbytes(vals[0]))
        return _ctx_group_sum(vals)

    def reduce_scatter_flat(self, value, num_shards, shard_index,
                            priority=0):
        """Local reduce-scatter: :meth:`allreduce_flat`'s whole-bucket
        replica sum, sliced to one 1/num_shards shard host-side."""
        vals = value if isinstance(value, (list, tuple)) else [value]
        vals = [v if isinstance(v, NDArray) else NDArray(v) for v in vals]
        n = int(vals[0].shape[0])
        if n % int(num_shards):
            raise MXNetError(
                f"reduce_scatter_flat: bucket length {n} not divisible "
                f"into {num_shards} shards (pad with pad_to_shards first)")
        step = n // int(num_shards)
        lo = step * int(shard_index)
        return self.allreduce_flat(vals, priority)[lo:lo + step]

    @property
    def fused_step_compatible(self):
        # the module's single-executor grads have no device replicas to
        # reduce — the sync is the identity. Gradient compression needs the
        # eager quantize/dequantize per push, so it keeps the eager path.
        return not self._gc.active

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only selected rows (reference PullRowSparseImpl
        `kvstore_dist.h:271`): the result has the full logical shape with
        the deduplicated requested rows filled, everything else zero —
        identical contract to the dist store."""
        import jax.numpy as jnp

        assert out is not None and row_ids is not None
        if isinstance(out, NDArray):
            out = [out]
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(out)
        key_list = [key] if isinstance(key, (str, int)) else key
        from .parallel.dist import _fill_rows

        for k, o, rid in zip(key_list * len(out), out, row_ids):
            src = self._store[k]
            ridx = rid._data.reshape(-1).astype(jnp.int32)
            uniq = jnp.unique(ridx) if ridx.size else jnp.zeros((0,), jnp.int32)
            _fill_rows(o, src._data, uniq)


def _str_key_int(k):
    """Deterministic int for string keys (updater state indexing) — must be
    stable across processes so saved optimizer states resume correctly
    (python hash() is per-process randomized)."""
    import zlib
    return zlib.crc32(str(k).encode("utf-8")) & 0x7FFFFFFF


class KVStore(KVStoreLocal):
    """Alias of the concrete store for isinstance checks (parity
    python/mxnet/kvstore.py class KVStore)."""


def create(name="local"):
    """Create a KVStore (parity kvstore.py:236 / factory kvstore.cc:40).

    Supported: 'local', 'device' (XLA-fused local reduce);
    'dist_sync'/'dist_device_sync'/'dist_tpu_sync' map to the SPMD
    collective store in `mxnet_tpu.parallel` (multi-host jax runtime);
    'dist_async' is intentionally unsupported on TPU (documented divergence
    — SURVEY.md §2.4)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu"):
        return KVStoreLocal(device=False)
    if name in ("device", "local_allreduce_device", "nccl"):
        return KVStoreLocal(device=True)
    if name.startswith("dist"):
        if "async" in name:
            raise MXNetError("dist_async is not supported by the TPU build: "
                             "synchronous SPMD collectives replace parameter servers "
                             "(SURVEY.md §5). Use dist_sync / dist_tpu_sync.")
        from .parallel.dist import KVStoreDistTPUSync
        return KVStoreDistTPUSync()
    raise MXNetError(f"unknown kvstore type {name}")
