"""Optimizer API (parity: `python/mxnet/optimizer/__init__.py`)."""
from . import optimizer
from .optimizer import *  # noqa: F401,F403

__all__ = optimizer.__all__
