"""Optimizer class zoo.

Parity: `python/mxnet/optimizer/optimizer.py` — Optimizer base (registry,
lr/wd mult, index→param maps, num_update counting):46; SGD:511, Signum:657,
FTML:724, NAG:1031, SGLD:1083, Adam:1120, AdaGrad:1204, RMSProp:1263,
AdaDelta:1341, Ftrl:1401, Adamax:1477, Nadam:1534; Updater:1621 (serializable
state used by kvstore servers), get_updater:1712.

Each optimizer calls the fused update ops (`src/operator/optimizer_op.cc`
equivalents in `mxnet_tpu/ops/optimizer_ops.py`): one XLA program per
(op, shape) — weight, grad and state stream through HBM exactly once.

Fused whole-step path: optimizers that define :meth:`Optimizer.fused_update`
(SGD, NAG, Adam — others fall back to the eager per-op loop automatically)
expose the update as a *pure function* ``(weights, grads, states, lrs, wds,
rescale) -> (new_weights, new_states)`` over raw jax arrays. The
:class:`Updater` jits ONE such program for the entire parameter set
(donating weight+state buffers so XLA updates them in place), and
``Module``'s fused train step traces the same function together with
forward+backward — the whole training step as one XLA computation.
Hyperparameters that change every step (lr schedules, Adam bias
correction, rescale_grad) are *traced arguments*, so a changing lr never
recompiles.
"""
from __future__ import annotations

import logging
import math
import os
import pickle
import warnings

import numpy

from ..base import MXNetError, getenv
from ..compile_cache import CompileCache
from ..ndarray import NDArray, zeros, ones, full
from .. import ndarray as nd


def _is_low_precision(dtype):
    """True for dtypes that want a fp32 master copy under multi_precision.
    The reference checks fp16 only (`optimizer.py:230`); on TPU the native
    half type is bfloat16, so it gets the same master-copy treatment."""
    name = numpy.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    return name in ("float16", "bfloat16")

__all__ = [
    "Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad",
    "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "LBSGD", "AdamW", "Test", "Updater",
    "get_updater", "register", "create",
]


class Optimizer:
    """The base class inherited by all optimizers (parity optimizer.py:46)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            warnings.warn(f"WARNING: New optimizer {klass.__name__}.{name} is overriding "
                          f"existing optimizer {Optimizer.opt_registry[name].__name__}.{name}")
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        """Instantiate an optimizer by registered name (parity :117)."""
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None, sym=None,
                 begin_num_update=0, multi_precision=False, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate

        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = 0

        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        self.param_dict = param_dict if param_dict else {}

        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        """Create auxiliary state for a given weight."""

    def create_state_multi_precision(self, index, weight):
        """Create aux state + fp32 master copy when multi_precision and
        weight is fp16 (parity :230)."""
        weight_master_copy = None
        if self.multi_precision and _is_low_precision(weight.dtype):
            weight_master_copy = weight.astype(numpy.float32)
            return (weight_master_copy,) + (self.create_state(index, weight_master_copy),)
        if _is_low_precision(weight.dtype) and not self.multi_precision:
            warnings.warn("Accumulating with float16 in optimizer can lead to "
                          "poor accuracy or slow convergence. "
                          "Consider using multi_precision=True option of the "
                          "optimizer")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        """Update weight given gradient and state."""
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_precision(weight.dtype):
            weight_master_copy = state[0]
            original_state = state[1]
            grad32 = grad.astype(numpy.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight[:] = weight_master_copy.astype(weight.dtype)
        else:
            self.update(index, weight, grad, state)

    # -- fused (jitted) whole-step update ------------------------------------
    #
    # The functional rendering of update_multi_precision over ALL parameters
    # at once: pure jax math over raw arrays, traceable inside one jitted
    # train step. Semantics must mirror the eager per-op path exactly (same
    # fp32 casts, same op order) — the eager loop stays the correctness
    # reference and tests/python/unittest/test_fused_step.py asserts parity.

    fused_update_supported = False

    def fused_update(self, weights, grads, states, lrs, wds, rescale_grad):
        """Pure functional update over raw jax arrays.

        ``weights``/``grads`` are lists of arrays; ``states`` the per-weight
        state trees from :meth:`create_state_multi_precision` with NDArray
        leaves replaced by arrays; ``lrs``/``wds`` per-weight scalars (traced
        — any step-dependent correction is already applied by
        :meth:`_fused_hyperparams`); ``rescale_grad`` a traced scalar.
        Returns ``(new_weights, new_states)`` with the same structure."""
        raise NotImplementedError(
            f"{type(self).__name__} has no fused update; the caller must "
            "check fused_update_supported and fall back to the eager loop")

    def _fused_hyperparams(self, indices):
        """Per-index (lrs, wds) with any update-count-dependent correction
        (e.g. Adam bias correction) applied host-side in float64 — exactly
        the numbers the eager path bakes into its op attrs. Call AFTER
        :meth:`_update_count`."""
        return self._get_lrs(indices), self._get_wds(indices)

    def _fused_static_key(self):
        """Everything trace-relevant that is NOT a traced argument — part of
        the CompileCache key, so mutating one of these recompiles instead of
        silently reusing a stale executable."""
        return (type(self).__name__, self.clip_gradient, self.multi_precision)

    def fused_state_init(self, w32, dtype):
        """Fresh optimizer state for ONE flat weight bucket of ``dtype``,
        as the tree :meth:`fused_update` expects for a single parameter —
        the traceable rendering of :meth:`create_state_multi_precision`
        over a packed bucket. ``w32`` is the fp32 cast of the bucket (the
        master copy under multi-precision). Used by the ZeRO-1 sharded
        update (`parallel/zero1.py`), which jits this with a dp-sharded
        output layout so only 1/N of the state ever materializes per
        replica; optimizers without it fall back to the replicated path."""
        raise NotImplementedError(
            f"{type(self).__name__} has no fused flat-state init; the "
            "caller must fall back to the replicated update")

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been defined. "
                              "Note that set_learning_rate can mutate the value of "
                              "the learning rate of the optimizer only when "
                              "the LRScheduler of the optimizer is undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """Set individual learning-rate multipliers (parity :330)."""
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Set individual weight-decay multipliers (parity :372). By default
        wd is not applied to biases/gamma/beta (names not ending in _weight
        or _gamma get 0 only via attr route in reference; gluon passes
        param_dict so wd_mult comes from Parameters)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lrs(self, indices):
        """Learning rates for indices (parity :437). The scheduler is
        consulted once per num_update value, not once per parameter/chunk —
        a 160-param step costs one scheduler call, not 160."""
        if self.lr_scheduler is not None:
            memo = getattr(self, "_lr_sched_memo", None)
            if memo is None or memo[0] != self.num_update:
                memo = (self.num_update, self.lr_scheduler(self.num_update))
                self._lr_sched_memo = memo
            lr = memo[1]
        else:
            lr = self.lr

        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        del ret["sym_info"]
        return ret

    def __setstate__(self, state):
        self.__dict__ = state
        self.sym_info = ()


register = Optimizer.register
create = Optimizer.create_optimizer


def _flatten_list(nested_list):
    return [item for sublist in nested_list for item in sublist]


def _sparse_sgd_update(weight, grad, state, lr, wd, rescale_grad,
                       clip_gradient, momentum):
    """Lazy (rows-only) SGD for row_sparse grads — the reference's
    sgd(_mom)_update with lazy_update=True on a row_sparse grad
    (`src/operator/optimizer_op.cc` SGDUpdateRspImpl): weight, momentum and
    wd touch ONLY the occupied rows; a 1M-row table costs O(batch) per step."""
    import jax.numpy as jnp

    rows = grad.indices._data.astype(jnp.int32)
    if rows.size == 0:
        return
    g = grad.data._data.astype(weight.dtype) * rescale_grad
    if clip_gradient:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w = weight._data
    wr = jnp.take(w, rows, axis=0)
    g = g + wd * wr
    if momentum and state is not None:
        m = state._data
        mr = jnp.take(m, rows, axis=0) * momentum - lr * g
        state._data = m.at[rows].set(mr)
        weight._data = w.at[rows].set(wr + mr)
    else:
        weight._data = w.at[rows].set(wr - lr * g)


def _sparse_adam_update(weight, grad, state, lr, wd, rescale_grad,
                        clip_gradient, beta1, beta2, epsilon):
    """Lazy (rows-only) Adam for row_sparse grads (reference
    AdamUpdateRspImpl, `optimizer_op.cc`): mean/var state rows decay only
    where the grad has rows."""
    import jax.numpy as jnp

    rows = grad.indices._data.astype(jnp.int32)
    if rows.size == 0:
        return
    g = grad.data._data.astype(weight.dtype) * rescale_grad
    if clip_gradient:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean, var = state
    w = weight._data
    wr = jnp.take(w, rows, axis=0)
    g = g + wd * wr
    mr = beta1 * jnp.take(mean._data, rows, axis=0) + (1 - beta1) * g
    vr = beta2 * jnp.take(var._data, rows, axis=0) + (1 - beta2) * g * g
    mean._data = mean._data.at[rows].set(mr)
    var._data = var._data.at[rows].set(vr)
    weight._data = w.at[rows].set(wr - lr * mr / (jnp.sqrt(vr) + epsilon))


@register
class SGD(Optimizer):
    """Stochastic gradient descent w/ momentum and multi-precision
    (parity optimizer.py:511; fused ops sgd_update/sgd_mom_update/mp_*)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        # fused multi-weight updates (reference optimizer.py:530: aggregation
        # over MXNET_OPTIMIZER_AGGREGATION_SIZE weights per multi_sgd_* call)
        self.aggregate_num = max(1, min(
            60, int(os.getenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", "4"))))

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and _is_low_precision(weight.dtype):
            weight_master_copy = weight.astype(numpy.float32)
            return (self.create_state(index, weight_master_copy), weight_master_copy)
        if _is_low_precision(weight.dtype) and not self.multi_precision:
            warnings.warn("Accumulating with float16 in optimizer can lead to "
                          "poor accuracy or slow convergence. "
                          "Consider using multi_precision=True option of the "
                          "SGD optimizer")
        return self.create_state(index, weight)

    def create_state(self, index, weight):
        momentum = None
        if self.momentum != 0.0:
            momentum = zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return momentum

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        aggregate = isinstance(index, (list, tuple))
        if aggregate:
            return self._update_aggregate(index, weight, grad, state,
                                          multi_precision)
        use_multi_precision = multi_precision and isinstance(state, (list, tuple))
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        kwargs = {"rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient

        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray) and self.lazy_update and \
                not use_multi_precision:
            _sparse_sgd_update(weight, grad, state, lr, wd, self.rescale_grad,
                               self.clip_gradient, self.momentum)
            return
        if not use_multi_precision:
            if state is not None:
                nd.sgd_mom_update(weight, grad, state, out=weight,
                                  lazy_update=self.lazy_update, **kwargs)
            else:
                nd.sgd_update(weight, grad, out=weight,
                              lazy_update=self.lazy_update, **kwargs)
        else:
            if state[0] is not None:
                nd.mp_sgd_mom_update(weight, grad, state[0], state[1], out=weight,
                                     lazy_update=self.lazy_update, **kwargs)
            else:
                nd.mp_sgd_update(weight, grad, state[1], out=weight,
                                 lazy_update=self.lazy_update, **kwargs)

    def _update_aggregate(self, indices, weights, grads, states,
                          multi_precision):
        """One fused multi_sgd_* call over a group of weights (reference
        optimizer.py:559-595 aggregate branch → `optimizer_op.cc`
        MultiSGDUpdate): a single XLA program streams every (weight, grad,
        state) through HBM, amortizing dispatch over the group."""
        self._update_count(indices)
        lrs = self._get_lrs(indices)
        wds = self._get_wds(indices)
        kwargs = {"rescale_grad": self.rescale_grad, "lrs": lrs, "wds": wds,
                  "num_weights": len(indices)}
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        if not multi_precision:
            if self.momentum > 0:
                data = _flatten_list(zip(weights, grads, states))
                nd.multi_sgd_mom_update(*data, out=list(weights), **kwargs)
            else:
                data = _flatten_list(zip(weights, grads))
                nd.multi_sgd_update(*data, out=list(weights), **kwargs)
        else:
            if self.momentum > 0:
                data = _flatten_list(
                    (w, g, s[0], s[1]) for w, g, s in zip(weights, grads, states))
                nd.multi_mp_sgd_mom_update(*data, out=list(weights), **kwargs)
            else:
                data = _flatten_list(
                    (w, g, s[1]) for w, g, s in zip(weights, grads, states))
                nd.multi_mp_sgd_update(*data, out=list(weights), **kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        if isinstance(index, (list, tuple)):
            use_multi_precision = self.multi_precision and \
                _is_low_precision(weight[0].dtype)
        else:
            use_multi_precision = self.multi_precision and \
                _is_low_precision(weight.dtype)
        self._update_impl(index, weight, grad, state,
                          multi_precision=use_multi_precision)

    fused_update_supported = True

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.momentum,)

    def fused_state_init(self, w32, dtype):
        """Flat-bucket state matching create_state_multi_precision: mp ->
        (momentum|None in fp32, master); else momentum|None in weight
        dtype."""
        import jax.numpy as jnp

        mp = self.multi_precision and _is_low_precision(dtype)
        mom = None
        if self.momentum != 0.0:
            mom = jnp.zeros_like(w32, dtype=jnp.float32 if mp else dtype)
        return (mom, w32) if mp else mom

    def fused_update(self, weights, grads, states, lrs, wds, rescale_grad):
        """Mirrors sgd_update / sgd_mom_update / mp_sgd_* (optimizer_ops.py)
        over the whole parameter list: fp32 math, results cast back."""
        import jax.numpy as jnp

        clip = float(self.clip_gradient) if self.clip_gradient else 0.0
        mom = float(self.momentum)
        new_ws, new_ss = [], []
        for w, g, s, lr, wd in zip(weights, grads, states, lrs, wds):
            mp = self.multi_precision and _is_low_precision(w.dtype)
            if mp:
                m, w32 = s  # create_state_multi_precision: (mom|None, master)
            else:
                m, w32 = s, w.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * rescale_grad
            if clip > 0:
                g32 = jnp.clip(g32, -clip, clip)
            g32 = g32 + wd * w32
            # branch on STATE PRESENCE exactly like the eager path's
            # `if state is not None: sgd_mom_update else sgd_update` — a
            # momentum later set to 0 still updates the existing state
            # (with mom==0), never nulls it
            if m is not None:
                new_m = mom * (m if mp else m.astype(jnp.float32)) - lr * g32
                new_w32 = w32 + new_m
            else:
                new_m = None
                new_w32 = w32 - lr * g32
            new_ws.append(new_w32.astype(w.dtype))
            if mp:
                new_ss.append((new_m, new_w32))
            else:
                new_ss.append(None if new_m is None else new_m.astype(m.dtype))
        return new_ws, new_ss


@register
class Signum(Optimizer):
    """SignSGD / Signum (parity optimizer.py:657)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        momentum = None
        if self.momentum != 0.0:
            momentum = zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return momentum

    def _update_impl(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        kwargs = {"rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        if self.wd_lh:
            kwargs["wd_lh"] = self.wd_lh

        if state is not None:
            nd.signum_update(weight, grad, state, out=weight, **kwargs)
        else:
            nd.signsgd_update(weight, grad, out=weight, **kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state)


@register
class FTML(Optimizer):
    """The FTML optimizer (parity optimizer.py:724)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # d_0
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # v_0
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))  # z_0

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]

        kwargs = {"lr": lr, "wd": wd, "t": t, "beta1": self.beta1, "beta2": self.beta2,
                  "epsilon": self.epsilon, "rescale_grad": self.rescale_grad}
        if self.clip_gradient:
            kwargs["clip_grad"] = self.clip_gradient
        prev_d, prev_v, prev_z = state
        nd.ftml_update(weight, grad, prev_d, prev_v, prev_z, out=weight, **kwargs)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity optimizer.py:975)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)

        mom, previous_weight = state
        if mom:
            mom[:] *= self.momentum
            mom[:] += -lr * (grad + wd * weight + self.lamda
                             * grad * grad * (weight - previous_weight))
        else:
            assert self.momentum == 0.0
            mom = -lr * (grad + wd * weight + self.lamda
                         * grad * grad * (weight - previous_weight))
        previous_weight[:] = weight
        weight[:] += mom


@register
class NAG(Optimizer):
    """Nesterov accelerated gradient (parity optimizer.py:1031)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        momentum = None
        if self.momentum != 0.0:
            momentum = zeros(weight.shape, weight.context, dtype=weight.dtype)
        return momentum

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = weight.astype(numpy.float32)
            return (self.create_state(index, weight_master_copy), weight_master_copy)
        return self.create_state(index, weight)

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        kwargs = {"rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient

        if not multi_precision:
            if state is not None:
                nd.nag_mom_update(weight, grad, state, out=weight, **kwargs)
            else:
                nd.sgd_update(weight, grad, out=weight, **kwargs)
        else:
            if state[0] is not None:
                nd.mp_nag_mom_update(weight, grad, state[0], state[1],
                                     out=weight, **kwargs)
            else:
                nd.mp_sgd_update(weight, grad, state[1], out=weight, **kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_multi_precision = self.multi_precision and weight.dtype == numpy.float16
        self._update_impl(index, weight, grad, state,
                          multi_precision=use_multi_precision)

    fused_update_supported = True

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.momentum,)

    def fused_state_init(self, w32, dtype):
        """Like SGD's, but NAG's multi-precision check is fp16-only
        (parity :1031)."""
        import jax.numpy as jnp

        mp = self.multi_precision and numpy.dtype(dtype) == numpy.float16
        mom = None
        if self.momentum != 0.0:
            mom = jnp.zeros_like(w32, dtype=jnp.float32 if mp else dtype)
        return (mom, w32) if mp else mom

    def fused_update(self, weights, grads, states, lrs, wds, rescale_grad):
        """Mirrors nag_mom_update / mp_nag_mom_update / sgd_update."""
        import jax.numpy as jnp

        clip = float(self.clip_gradient) if self.clip_gradient else 0.0
        mom = float(self.momentum)
        new_ws, new_ss = [], []
        for w, g, s, lr, wd in zip(weights, grads, states, lrs, wds):
            # NAG's eager mp check is fp16-only (parity :1031)
            mp = self.multi_precision and numpy.dtype(w.dtype) == numpy.float16
            if mp:
                m, w32 = s
            else:
                m, w32 = s, w.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * rescale_grad
            if clip > 0:
                g32 = jnp.clip(g32, -clip, clip)
            g32 = g32 + wd * w32
            # state presence decides the branch (eager: `if state is not
            # None: nag_mom_update`), so a zeroed momentum keeps its state
            if m is not None:
                new_m = mom * (m if mp else m.astype(jnp.float32)) + g32
                new_w32 = w32 - lr * (g32 + mom * new_m)
            else:
                new_m = None
                new_w32 = w32 - lr * g32
            new_ws.append(new_w32.astype(w.dtype))
            if mp:
                new_ss.append((new_m, new_w32))
            else:
                new_ss.append(None if new_m is None else new_m.astype(m.dtype))
        return new_ws, new_ss


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity optimizer.py:1083)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        weight[:] += -lr / 2 * (grad + wd * weight)
        weight[:] += nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                      dtype=weight.dtype, ctx=weight.context)


@register
class Adam(Optimizer):
    """Adam (parity optimizer.py:1120; fused op adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # mean
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # variance

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1

        kwargs = {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
                  "rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient

        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray) and self.lazy_update:
            _sparse_adam_update(weight, grad, state, lr, wd, self.rescale_grad,
                                self.clip_gradient, self.beta1, self.beta2,
                                self.epsilon)
            return

        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=weight,
                       lazy_update=self.lazy_update, **kwargs)

    fused_update_supported = True

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.beta1, self.beta2,
                                              self.epsilon)

    def fused_state_init(self, w32, dtype):
        """Flat-bucket state matching the base-class multi-precision
        convention: mp -> (master, (mean, var) in fp32); else (mean, var)
        in weight dtype."""
        import jax.numpy as jnp

        mp = self.multi_precision and _is_low_precision(dtype)
        sd = jnp.float32 if mp else dtype
        mean = jnp.zeros_like(w32, dtype=sd)
        var = jnp.zeros_like(w32, dtype=sd)
        return (w32, (mean, var)) if mp else (mean, var)

    def _fused_hyperparams(self, indices):
        """Bias correction applied host-side in float64 — bit-identical to
        the lr the eager update() bakes into adam_update's attrs."""
        lrs, wds = super()._fused_hyperparams(indices)
        out = []
        for lr, index in zip(lrs, indices):
            t = self._index_update_count[index]
            coef1 = 1. - self.beta1 ** t
            coef2 = 1. - self.beta2 ** t
            out.append(lr * math.sqrt(coef2) / coef1)
        return out, wds

    def fused_update(self, weights, grads, states, lrs, wds, rescale_grad):
        """Mirrors adam_update (optimizer_ops.py) with the base-class
        multi-precision convention: state = (master, (mean, var))."""
        import jax.numpy as jnp

        clip = float(self.clip_gradient) if self.clip_gradient else 0.0
        b1, b2, eps = float(self.beta1), float(self.beta2), float(self.epsilon)
        new_ws, new_ss = [], []
        for w, g, s, lr, wd in zip(weights, grads, states, lrs, wds):
            mp = self.multi_precision and _is_low_precision(w.dtype)
            if mp:
                w32, (mean, var) = s
            else:
                w32, (mean, var) = w.astype(jnp.float32), s
            g32 = g.astype(jnp.float32) * rescale_grad
            if clip > 0:
                g32 = jnp.clip(g32, -clip, clip)
            g32 = g32 + wd * w32
            new_mean = b1 * mean.astype(jnp.float32) + (1 - b1) * g32
            new_var = b2 * var.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
            new_w32 = w32 - lr * new_mean / (jnp.sqrt(new_var) + eps)
            new_ws.append(new_w32.astype(w.dtype))
            if mp:
                new_ss.append((new_w32, (new_mean, new_var)))
            else:
                new_ss.append((new_mean.astype(mean.dtype),
                               new_var.astype(var.dtype)))
        return new_ws, new_ss


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (contrib `adamw.cc`; the transformer
    default — north-star config 3)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.eta = eta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        kwargs = {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
                  "rescale_grad": self.rescale_grad, "lr": lr, "wd": wd, "eta": self.eta}
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        mean, var = state
        nd.contrib_adamw_update(weight, grad, mean, var, out=weight, **kwargs)


@register
class AdaGrad(Optimizer):
    """AdaGrad (parity optimizer.py:1204)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, stype=weight.stype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        history = state
        history[:] += nd.square(grad)
        div = grad / nd.sqrt(history + self.float_stable_eps)
        weight[:] += (div + weight * wd) * -lr


@register
class RMSProp(Optimizer):
    """RMSProp (parity optimizer.py:1263; centered=True uses Graves 2013)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context, stype=weight.stype),  # n
                    zeros(weight.shape, weight.context, stype=weight.stype),  # g
                    zeros(weight.shape, weight.context, stype=weight.stype))  # delta
        return (zeros(weight.shape, weight.context, stype=weight.stype),)  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        kwargs = {"gamma1": self.gamma1, "epsilon": self.epsilon,
                  "rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.centered:
            kwargs["gamma2"] = self.gamma2
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        if self.clip_weights:
            kwargs["clip_weights"] = self.clip_weights

        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, out=weight, **kwargs)


@register
class AdaDelta(Optimizer):
    """AdaDelta (parity optimizer.py:1341)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),  # accumulated g
                zeros(weight.shape, weight.context))  # accumulated delta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)

        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)

        acc_g, acc_delta = state
        acc_g[:] *= self.rho
        acc_g[:] += (1. - self.rho) * grad * grad
        current_delta = nd.sqrt(acc_delta + self.epsilon) / \
            nd.sqrt(acc_g + self.epsilon) * grad
        acc_delta[:] *= self.rho
        acc_delta[:] += (1. - self.rho) * current_delta * current_delta
        weight[:] -= current_delta + wd * weight


@register
class Ftrl(Optimizer):
    """FTRL (parity optimizer.py:1401; fused op ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, stype=weight.stype),  # z
                zeros(weight.shape, weight.context, stype=weight.stype))  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        lr = self._get_lr(index)

        kwargs = {"lamda1": self.lamda1, "beta": self.beta,
                  "rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient

        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=weight, **kwargs)


@register
class Adamax(Optimizer):
    """AdaMax, infinity-norm Adam variant (parity optimizer.py:1477)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # mean
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # variance

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)

        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)

        m_t, u_t = state
        m_t[:] *= self.beta1
        m_t[:] += (1. - self.beta1) * grad
        u_t[:] = nd.maximum(self.beta2 * u_t, nd.abs(grad))
        weight[:] -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    """Nesterov Adam (parity optimizer.py:1534)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # mean
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # variance

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]

        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)

        momentum_t = self.beta1 * (1. - 0.5 * (pow(0.96, t * self.schedule_decay)))
        momentum_t_1 = self.beta1 * (1. - 0.5 * (pow(0.96, (t + 1) * self.schedule_decay)))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1

        m_t, v_t = state
        m_t[:] *= self.beta1
        m_t[:] += (1. - self.beta1) * grad
        v_t[:] *= self.beta2
        v_t[:] += (1. - self.beta2) * grad * grad

        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - pow(self.beta2, t))
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime

        weight[:] -= lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS layer-wise adaptive rates
    (parity optimizer.py:782; simplified warmup strategies)."""

    def __init__(self, momentum=0.0, multi_precision=False, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(**kwargs)
        logging.info("Running Large-Batch SGD Algorithm")
        logging.info("(Batch_scale=%f, warmup_epochs=%d, warmup_strategy=%s, "
                     "updates_per_epoch=%d)", batch_scale, warmup_epochs,
                     warmup_strategy, updates_per_epoch)
        self.momentum = momentum
        self.multi_precision = multi_precision
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1
        self.cumgrads = {}
        self.adaptive = False
        self.admult = 1

    def create_state(self, index, weight):
        momentum = None
        weight_master_copy = None
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = weight.astype(numpy.float32)
            if self.momentum != 0.0:
                momentum = zeros(weight.shape, weight.context, dtype=numpy.float32)
            return (momentum, weight_master_copy)
        if self.momentum != 0.0:
            momentum = zeros(weight.shape, weight.context, dtype=weight.dtype)
        return momentum

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        strategy = self.warmup_strategy
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            mult = maxmult
        elif nwup <= 1:
            mult = 1.0
        else:
            if strategy == "linear":
                mult = 1.0 + (maxmult - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
            else:
                mult = 1.0
        return mult

    def _get_lars(self, weight, g, wd):
        """LARS trust ratio for one weight."""
        weight2 = (weight * weight).sum().asscalar()
        grad2 = (g * g).sum().asscalar()
        lars = math.sqrt(weight2 / (grad2 + wd * weight2 + 1e-18))
        if lars < 0.01:
            lars = 0.01
        elif lars > 100:
            lars = 100
        return lars

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)

        if self.warmup_strategy == "lars":
            lbmult = self._get_lars(weight, grad, wd)
        else:
            lbmult = self._get_lbmult(self.num_update)
        lr = lr * lbmult

        kwargs = {"rescale_grad": self.rescale_grad, "lr": lr, "wd": wd}
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient

        use_multi_precision = isinstance(state, (list, tuple))
        if not use_multi_precision:
            if state is not None:
                nd.sgd_mom_update(weight, grad, state, out=weight, **kwargs)
            else:
                nd.sgd_update(weight, grad, out=weight, **kwargs)
        else:
            if state[0] is not None:
                nd.mp_sgd_mom_update(weight, grad, state[0], state[1], out=weight,
                                     **kwargs)
            else:
                nd.mp_sgd_update(weight, grad, state[1], out=weight, **kwargs)


@register
class Test(Optimizer):
    """Simple test optimizer (parity optimizer.py Test)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight[:] += grad * self.rescale_grad
        state[:] = weight


create = Optimizer.create_optimizer


def _state_sig(s):
    """Hashable shape/dtype signature of one state tree (CompileCache key).
    Built every step — dtype objects, not strings."""
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_state_sig(x) for x in s)
    return (s._data.shape, s._data.dtype)


def _state_to_jax(s):
    """NDArray leaves -> raw jax arrays (same structure)."""
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_state_to_jax(x) for x in s)
    return s._data


def _state_writeback(s, new):
    """Swap each NDArray leaf's buffer for the corresponding new array —
    the functional rendering of the reference's in-place state mutation.
    A None in ``new`` against a live leaf means the update did not touch
    that state — keep the old buffer, never null a live NDArray."""
    if s is None or new is None:
        return
    if isinstance(s, (tuple, list)):
        for a, b in zip(s, new):
            _state_writeback(a, b)
    else:
        s._data = new


def _snapshot_counts(opt, indices):
    """Snapshot update-count bookkeeping so a fused step that fails BEFORE
    executing (trace/compile error — buffers untouched) can fall back to
    the eager loop without double-counting the step."""
    return (opt.num_update,
            {i: opt._index_update_count.get(i) for i in indices})


def _restore_counts(opt, snap):
    num_update, counts = snap
    for i, v in counts.items():
        if v is None:
            opt._index_update_count.pop(i, None)
        else:
            opt._index_update_count[i] = v
    opt.num_update = num_update


def _any_donated_deleted(arrays):
    """True when any donated input buffer was actually consumed — the line
    between 'retry eagerly' (trace/compile failed, weights intact) and
    'weights are gone, restore from checkpoint'."""
    out = False
    for a in arrays:
        try:
            out = out or a.is_deleted()
        except Exception:  # noqa: BLE001 — conservative: treat as deleted
            out = True
    return out


# one executable per (optimizer fingerprint, weight shapes/dtypes, state
# structure) — shared across Updater instances (gluon Trainer keeps one
# Updater per context; all hit the same cache). Bounded: each entry's build
# closure pins its Optimizer instance, so a long-lived process cycling
# through many Trainers must not accumulate them forever (oldest out)
_fused_updater_cache = None


def _updater_cache():
    global _fused_updater_cache
    if _fused_updater_cache is None:
        _fused_updater_cache = CompileCache("optimizer.fused_update",
                                            maxsize=64)
    return _fused_updater_cache


class Updater:
    """Updater for kvstore (parity optimizer.py:1621): holds per-key states,
    serializable so a kvstore server process can resume it."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0
        # set after a fused trace/compile failure: stop re-paying the
        # failed trace every step and stay on the eager loop
        self._fused_disabled = False
        # ZeRO-1 sharded-update context (parallel/zero1.py): owns the
        # dp-sharded flat optimizer state when MXNET_ZERO1=1
        self._zero1 = None
        self._zero1_failed = False
        # memory census: the replicated per-parameter states (the sharded
        # ones census through the Zero1Context's own provider). A live
        # view — fused updates replace the state arrays every step.
        from .. import memory
        from jax import tree_util as _jtu

        memory.register_provider(
            "optimizer_state", self,
            lambda s: [leaf for st in s.states.values()
                       for leaf in _jtu.tree_leaves(st)
                       if hasattr(leaf, "nbytes") or hasattr(leaf, "_data")])

    def ensure_states(self, indices, weights):
        """Create (or context-sync) the optimizer state for each index —
        the lazy-creation half of ``__call__``, callable on its own by the
        fused train step (which needs the states before tracing)."""
        z1 = getattr(self, "_zero1", None)
        if z1 is not None and z1.dirty:
            # a sharded run handing over to an eager/replicated step (or a
            # checkpoint save): gather the shards into the per-parameter
            # states FIRST, or this path would consume stale ones
            z1.export_to_updater(self)
        for i, idx in enumerate(indices):
            if idx not in self.states:
                self.states[idx] = self.optimizer.create_state_multi_precision(
                    idx, weights[i])
                self.states_synced[idx] = True
            elif not self.states_synced[idx]:
                self.states[idx] = self.sync_state_context(self.states[idx],
                                                           weights[i].context)
                self.states_synced[idx] = True

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices = [index]
            grads = [grad]
            weights = [weight]
        else:
            indices = index
            grads = grad
            weights = weight
        if len(indices) > 1 and self._fused_call(indices, grads, weights):
            return
        self.ensure_states(indices, weights)
        if self.aggregate_updates and len(indices) > 1:
            self._aggregated_update(indices, grads, weights)
            return
        for i, idx in enumerate(indices):
            self.optimizer.update_multi_precision(idx, weights[i], grads[i],
                                                  self.states[idx])

    def _fused_call(self, indices, grads, weights):
        """One jitted Optimizer.fused_update over the whole parameter group
        with weight and state buffers donated — the entire optimizer step is
        a single XLA computation instead of one dispatch per (chunk of)
        parameters. Returns False (caller falls back to the eager loop) for
        optimizers without a fused path, sparse grads, or MXNET_FUSED_STEP=0
        — the eager loop remains the correctness reference."""
        opt = self.optimizer
        if self._fused_disabled or not opt.fused_update_supported \
                or not getenv("MXNET_FUSED_STEP"):
            return False
        from ..ndarray.sparse import RowSparseNDArray

        if any(isinstance(g, RowSparseNDArray) or isinstance(w, RowSparseNDArray)
               for g, w in zip(grads, weights)):
            return False

        from ..parallel.zero1 import zero1_enabled

        if zero1_enabled() and not getattr(self, "_zero1_failed", False):
            took = self._zero1_call(indices, grads, weights)
            if took is not None:
                return took
            # zero1 declined (unsupported optimizer / trace failure with
            # buffers intact): fall through to the replicated fused path

        import jax
        import jax.numpy as jnp

        self.ensure_states(indices, weights)
        count_snap = _snapshot_counts(opt, indices)
        opt._update_count(indices)
        try:
            lrs, wds = opt._fused_hyperparams(indices)
            states = [self.states[idx] for idx in indices]
            key = (opt._fused_static_key(),
                   tuple((w._data.shape, w._data.dtype) for w in weights),
                   tuple((g._data.shape, g._data.dtype) for g in grads),
                   tuple(_state_sig(s) for s in states))

            def build():
                from ..compile_cache import trace_salt

                def step(ws, gs, ss, lrs_, wds_, rescale):
                    # salt the HLO: this donated program must never be
                    # deserialized by another process
                    # (compile_cache.trace_salt)
                    return opt.fused_update(ws, gs, ss, lrs_, wds_,
                                            trace_salt(rescale))

                return jax.jit(step, donate_argnums=(0, 2))

            # persistent=False: donated programs must stay OUT of the
            # on-disk XLA cache (deserialized aliasing corrupts the heap —
            # see CompileCache.get_or_build)
            fn = _updater_cache().get_or_build(key, build, persistent=False)
            new_ws, new_ss = fn([w._data for w in weights],
                                [g._data for g in grads],
                                [_state_to_jax(s) for s in states],
                                jnp.asarray(lrs, jnp.float32),
                                jnp.asarray(wds, jnp.float32),
                                jnp.float32(opt.rescale_grad))
        except Exception as e:
            if _any_donated_deleted(w._data for w in weights):
                # execution consumed donated inputs before failing —
                # weights/states are unrecoverable in-process
                raise MXNetError(
                    "fused optimizer update failed mid-execution; weight/"
                    "state buffers were donated and may be invalidated — "
                    "restore from the last checkpoint before continuing "
                    f"({e!r})") from e
            # trace/compile failed BEFORE any buffer was consumed (e.g. an
            # Optimizer subclass whose states the fused path can't unpack):
            # weights are intact — undo the count bump and stay eager
            _restore_counts(opt, count_snap)
            self._fused_disabled = True
            logging.getLogger("mxnet_tpu.optimizer").warning(
                "fused update failed to build (%r); falling back to the "
                "eager per-op update loop", e)
            return False
        for w, nw in zip(weights, new_ws):
            w._data = nw
        for s, ns in zip(states, new_ss):
            _state_writeback(s, ns)
        return True

    def _zero1_call(self, indices, grads, weights):
        """ZeRO-1 variant of :meth:`_fused_call` (`MXNET_ZERO1=1`): ONE
        jitted program whose weight update runs on each replica's 1/N
        shard of the flat parameter buckets with 1/N optimizer state
        (`parallel/zero1.py`), weights allgathered back replicated.
        Returns True when taken, None to fall through to the replicated
        fused path (buffers intact)."""
        import jax.numpy as jnp

        from ..parallel.zero1 import Zero1Context

        opt = self.optimizer
        if self._zero1 is None:
            try:
                self._zero1 = Zero1Context()
            except Exception as e:  # noqa: BLE001 — bad mesh/env (e.g.
                # MXNET_ZERO1_NDEV > device count): no buffer was touched,
                # stay on the replicated fused path
                self._zero1_failed = True
                logging.getLogger("mxnet_tpu.optimizer").warning(
                    "ZeRO-1 context unavailable (%r); falling back to the "
                    "replicated fused update", e)
                return None
        ctx = self._zero1
        count_snap = _snapshot_counts(opt, indices)
        opt._update_count(indices)
        try:
            lrs, wds = opt._fused_hyperparams(indices)
            ctx.ensure(opt, self, indices, weights)
            key = ("zero1", ctx.key(), opt._fused_static_key(),
                   tuple((w._data.shape, w._data.dtype) for w in weights),
                   tuple((g._data.shape, g._data.dtype) for g in grads))

            def build():
                import jax

                from ..compile_cache import trace_salt

                def step(ws, gs, flat, lrs_, wds_, rescale):
                    return ctx.traced_update(opt, list(ws), list(gs), flat,
                                             lrs_, wds_, trace_salt(rescale))

                # donate only the flat sharded state: the updated weights
                # are slices of one all-gathered bucket, which XLA cannot
                # reliably alias into the k donated weight buffers (the
                # hlolint donation audit showed it declining silently) —
                # declared donations must actually alias
                return jax.jit(step, donate_argnums=(2,))

            # audit="zero1": this is the gluon/aggregated rendering of the
            # sharded update — same reduce-scatter/all-gather contract row
            # as the executor-side fused step (tools/hlolint/contracts.py)
            fn = _updater_cache().get_or_build(key, build, persistent=False,
                                               audit="zero1")
            new_ws, new_flat = fn(
                [ctx.put_replicated(w._data) for w in weights],
                [ctx.put_replicated(g._data) for g in grads],
                ctx.flat_states,
                ctx.put_replicated(jnp.asarray(lrs, jnp.float32)),
                ctx.put_replicated(jnp.asarray(wds, jnp.float32)),
                ctx.put_replicated(jnp.float32(opt.rescale_grad)))
        except Exception as e:
            from jax import tree_util as jtu

            # the sharded flat state was donated too — and it is the ONLY
            # copy once dirty, so a consumed state buffer is just as fatal
            # as a consumed weight
            donated = [w._data for w in weights]
            donated += jtu.tree_leaves(ctx.flat_states or [])
            if _any_donated_deleted(donated):
                raise MXNetError(
                    "ZeRO-1 fused update failed mid-execution; weight/"
                    "state buffers were donated and may be invalidated — "
                    "restore from the last checkpoint before continuing "
                    f"({e!r})") from e
            # trace/compile failed before any buffer was consumed: undo the
            # count bump and let the replicated fused path take the step
            _restore_counts(opt, count_snap)
            self._zero1_failed = True
            logging.getLogger("mxnet_tpu.optimizer").warning(
                "ZeRO-1 sharded update failed to build (%r); falling back "
                "to the replicated fused update", e)
            return None
        for w, nw in zip(weights, new_ws):
            w._data = nw
        ctx.flat_states = new_flat
        ctx.dirty = True
        return True

    def _aggregated_update(self, indices, grads, weights):
        """Group same-dtype dense updates into multi_sgd_*-sized chunks
        (parity optimizer.py:1637-1664: the aggregate_updates branch of
        Updater.__call__; dtype segregation then aggregate_num chunking)."""
        from ..ndarray.sparse import RowSparseNDArray

        by_type = {}
        order = []
        for idx, g, w in zip(indices, grads, weights):
            if isinstance(g, RowSparseNDArray):
                # sparse updates keep the per-key lazy path
                self.optimizer.update_multi_precision(idx, w, g,
                                                      self.states[idx])
                continue
            key = str(w.dtype)
            if key not in by_type:
                by_type[key] = []
                order.append(key)
            by_type[key].append((idx, g, w))
        step = self.optimizer.aggregate_num
        for key in order:
            group = by_type[key]
            for start in range(0, len(group), step):
                chunk = group[start:start + step]
                idxs = [c[0] for c in chunk]
                self.optimizer.update_multi_precision(
                    idxs, [c[2] for c in chunk], [c[1] for c in chunk],
                    [self.states[i] for i in idxs])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            synced_state = (self.sync_state_context(i, context) for i in state)
            if isinstance(state, tuple):
                return tuple(synced_state)
            return list(synced_state)
        return state

    def set_states(self, states):
        """Set updater states from serialized bytes. A live ZeRO-1 context
        is invalidated so the next sharded step re-shards the LOADED
        per-parameter states instead of keeping pre-load shards."""
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)
        z1 = getattr(self, "_zero1", None)
        if z1 is not None:
            z1.invalidate()

    def get_states(self, dump_optimizer=False):
        """Serialized states. Under ZeRO-1 the shards are gathered back
        into ordinary per-parameter states first (checkpoints stay
        store-format-identical to replicated runs; loading re-shards)."""
        z1 = getattr(self, "_zero1", None)
        if z1 is not None and z1.dirty:
            z1.export_to_updater(self)
        return pickle.dumps((self.states, self.optimizer) if dump_optimizer
                            else self.states)


def get_updater(optimizer):
    """Return a closure of the updater needed for kvstore (parity :1712)."""
    return Updater(optimizer)
