"""2-bit gradient compression with error-feedback residual.

Parity: `src/kvstore/gradient_compression.cc:45-113` (`SetParams`,
`SetTwoBitCompression`, `Quantize`/`Dequantize`) and the element kernel
`quantize_2bit` in `src/kvstore/gradient_compression-inl.h:40-80`:

    residual += grad
    if residual >=  threshold: emit code 11, residual -= threshold
    if residual <= -threshold: emit code 10, residual += threshold
    else:                      emit code 00 (value dropped, kept in residual)

Sixteen 2-bit codes pack into one 32-bit word (the reference packs into a
float32's bytes, MSB-first within each byte; we pack LSB-first into a
uint32 — the wire format is ours, the arithmetic is bit-for-bit the same
and is what the tests pin down, reproducing the reference's own expected-
value simulation `tests/nightly/test_kvstore.py:33`
``compute_expected_2bit_quantization``).

TPU-native design: quantize/dequantize are pure jitted functions (fused by
XLA into the push program) plus a Pallas kernel for the quantize hot path
(`quantize_2bit_pallas`) — grid over 128-lane tiles, pack via a 16-step
shift-or in registers. Dequantize(sum-over-workers) runs as one fused XLA
program on the allgathered packed words (`parallel/dist.py`).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["GradientCompression", "quantize_2bit", "dequantize_2bit",
           "quantize_2bit_pallas"]

_VALS_PER_WORD = 16  # 32 bits / 2 bits per value (GetCompressionFactor, gradient_compression.cc:86)


def compressed_size(n):
    """Number of uint32 words for n values (`GetCompressedSize`,
    gradient_compression.cc:94-99)."""
    return (n + _VALS_PER_WORD - 1) // _VALS_PER_WORD


@functools.partial(jax.jit, static_argnames=("threshold",))
def quantize_2bit(grad, residual, threshold):
    """Error-feedback 2-bit quantization.

    Returns ``(packed uint32[ceil(n/16)], new_residual)``. Gradient + residual
    maps to {-threshold, 0, +threshold}; the rounding error stays in the
    residual (`gradient_compression-inl.h:66-79`).
    """
    r = residual + grad.astype(residual.dtype)
    pos = r >= threshold
    neg = r <= -threshold
    new_residual = jnp.where(pos, r - threshold, jnp.where(neg, r + threshold, r))
    codes = jnp.where(pos, jnp.uint32(3), jnp.where(neg, jnp.uint32(2), jnp.uint32(0)))
    flat = codes.reshape(-1)
    pad = (-flat.shape[0]) % _VALS_PER_WORD
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.uint32)])
    blocks = flat.reshape(-1, _VALS_PER_WORD)
    shifts = (jnp.arange(_VALS_PER_WORD, dtype=jnp.uint32) * 2)[None, :]
    packed = jnp.bitwise_or.reduce(blocks << shifts, axis=1)
    return packed, new_residual


@functools.partial(jax.jit, static_argnames=("shape", "threshold", "dtype"))
def dequantize_2bit(packed, shape, threshold, dtype=jnp.float32):
    """Inverse map: code 11 → +threshold, 10 → -threshold, else 0
    (`Dequantize2BitImpl`, gradient_compression-inl.h:83-...)."""
    n = int(np.prod(shape))
    shifts = (jnp.arange(_VALS_PER_WORD, dtype=jnp.uint32) * 2)[None, :]
    codes = (packed[:, None] >> shifts) & jnp.uint32(3)
    flat = codes.reshape(-1)[:n]
    out = jnp.where(flat == 3, jnp.asarray(threshold, dtype),
                    jnp.where(flat == 2, jnp.asarray(-threshold, dtype),
                              jnp.asarray(0, dtype)))
    return out.reshape(shape)


def quantize_2bit_pallas(grad, residual, threshold):
    """Pallas TPU kernel for the quantize hot path (SURVEY §7's showcase):
    one grid step packs a 2048-value tile (keeps lanes ×16 sublanes busy)
    into 128 uint32 words with the shift-or tree in registers.

    Falls back to interpret mode off-TPU so the same kernel is testable on
    the CPU suite; numerics are identical to :func:`quantize_2bit`.
    """
    from jax.experimental import pallas as pl

    n = grad.size
    flat_g = grad.reshape(-1).astype(jnp.float32)
    flat_r = residual.reshape(-1).astype(jnp.float32)
    tile = 2048
    padded = ((n + tile - 1) // tile) * tile
    if padded != n:
        flat_g = jnp.concatenate([flat_g, jnp.zeros((padded - n,), jnp.float32)])
        flat_r = jnp.concatenate([flat_r, jnp.zeros((padded - n,), jnp.float32)])
    n_tiles = padded // tile
    words_per_tile = tile // _VALS_PER_WORD

    def kernel(g_ref, r_ref, packed_ref, res_ref, *, threshold):
        g = g_ref[...]
        r = r_ref[...] + g
        pos = r >= threshold
        neg = r <= -threshold
        res_ref[...] = jnp.where(pos, r - threshold, jnp.where(neg, r + threshold, r))
        codes = jnp.where(pos, jnp.uint32(3), jnp.where(neg, jnp.uint32(2), jnp.uint32(0)))
        blocks = codes.reshape(words_per_tile, _VALS_PER_WORD)
        shifts = (jnp.arange(_VALS_PER_WORD, dtype=jnp.uint32) * 2)[None, :]
        packed_ref[...] = jnp.bitwise_or.reduce(blocks << shifts, axis=1)

    interpret = jax.default_backend() != "tpu"
    packed, new_res = pl.pallas_call(
        functools.partial(kernel, threshold=float(threshold)),
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((tile,), lambda i: (i,)),
                  pl.BlockSpec((tile,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((words_per_tile,), lambda i: (i,)),
                   pl.BlockSpec((tile,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((padded // _VALS_PER_WORD,), jnp.uint32),
                   jax.ShapeDtypeStruct((padded,), jnp.float32)],
        interpret=interpret,
    )(flat_g, flat_r)
    return packed[:compressed_size(n)], new_res[:n].reshape(residual.shape).astype(residual.dtype)


class GradientCompression:
    """Per-kvstore compression state (`GradientCompression`,
    gradient_compression.h / .cc:40-63). Holds the per-key error-feedback
    residuals — one per worker, exactly like the reference keeps a residual
    NDArray per compressed key on the worker (`kvstore_dist.h` comm buffers).
    """

    def __init__(self):
        self.type = None
        self.threshold = 0.5
        self._residuals = {}

    def set_params(self, compression_params):
        params = dict(compression_params)
        ctype = params.pop("type", None)
        threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError(f"unknown gradient compression params {sorted(params)}")
        if ctype != "2bit":
            raise MXNetError(f"Unknown type for gradient compression {ctype}")
        if threshold <= 0:
            raise MXNetError("threshold must be greater than 0")
        self.type = "2bit"
        self.threshold = threshold

    @property
    def active(self):
        return self.type == "2bit"

    def quantize(self, key, grad):
        """Quantize ``grad`` for ``key``, folding in and updating the
        residual. Returns packed uint32 words."""
        res = self._residuals.get(key)
        if res is None or res.shape != grad.shape:
            res = jnp.zeros(grad.shape, jnp.float32)
        packed, new_res = quantize_2bit(jnp.asarray(grad), res, self.threshold)
        self._residuals[key] = new_res
        return packed

    def dequantize(self, packed, shape, dtype=jnp.float32):
        return dequantize_2bit(packed, tuple(shape), self.threshold, dtype)
