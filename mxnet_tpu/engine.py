"""Host-side execution engine.

Parity: `src/engine/` (NaiveEngine / ThreadedEnginePerDevice) + Python
`python/mxnet/engine.py` (bulk scope).

TPU-native redesign (SURVEY.md §7): **on-device** ordering/fusion is the
compiled XLA program — jax dispatches asynchronously and XLA's runtime owns
device streams, so the reference's dependency-variable scheduler is not
re-implemented for compute. What remains host-side is ordering of IO,
checkpoint and collective-issue work; that engine lives in the native C++
runtime (``src/engine.cc`` via :mod:`mxnet_tpu.lib`) with this module
exposing the reference's Python surface (bulk, engine-type query).
"""
from __future__ import annotations

import contextlib

from .base import getenv

__all__ = ["bulk", "engine_type", "push", "wait_all"]


def engine_type():
    return getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


@contextlib.contextmanager
def bulk(size):
    """Parity `mx.engine.bulk`: a no-op scope on TPU — XLA whole-program
    compilation is the limit case of engine bulking (`threaded_engine.h:413`)."""
    yield


def push(fn, *args, **kwargs):
    """Push host-side async work onto the native engine (falls back to inline
    execution when the native library is unavailable)."""
    from . import lib

    eng = lib.native_engine()
    if eng is not None:
        return eng.push(fn, args, kwargs)
    fn(*args, **kwargs)
    return None


def wait_all():
    from . import lib
    from .ndarray import waitall

    eng = lib.native_engine()
    if eng is not None:
        eng.wait_all()
    waitall()
