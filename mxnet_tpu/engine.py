"""Host-side execution engine.

Parity: `src/engine/` (NaiveEngine / ThreadedEnginePerDevice) + Python
`python/mxnet/engine.py` (bulk scope).

TPU-native redesign (SURVEY.md §7): **on-device** ordering/fusion is the
compiled XLA program — jax dispatches asynchronously and XLA's runtime owns
device streams, so the reference's dependency-variable scheduler is not
re-implemented for compute. What remains host-side is ordering of IO,
checkpoint and prefetch work; that engine lives in the native C++ runtime
(``src/engine.cc`` via :mod:`mxnet_tpu.lib`) and THIS module is its
production frontend: `nd.save` / `save_checkpoint` push file writes here
with per-path write-var ordering (reference Engine::PushAsync with a
mutable var per resource, `src/engine/threaded_engine.cc`), and
`io.PrefetchingIter` pushes batch fetches with a per-iterator var.
"""
from __future__ import annotations

import atexit
import contextlib
import threading
import time

from . import analysis
from . import telemetry
from . import tracing
from .base import getenv

__all__ = ["bulk", "engine_type", "push", "push_io", "wait_all", "path_var"]

_io_state = threading.local()
_path_vars = {}
_var_pool = []
# epoch-numbered checkpoints create unbounded distinct paths; past this
# many live path→var entries the engine is drained and every var recycled
# (safe: after wait_all no write is in flight, so remapping a var to a new
# path cannot reorder anything)
_PATH_VAR_CAP = 512
_path_lock = analysis.make_lock("engine.path_vars")
# exceptions raised by async-pushed fns; re-raised at the next wait_all()
# so failures are not silently swallowed (the reference engine aborts the
# process on an op error — here the error surfaces at the sync point)
_async_error = []


def engine_type():
    return getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def async_io_enabled():
    """Async file IO through the native engine is ON by default when the
    native library is built; `MXNET_ENGINE_ASYNC_IO=0` forces synchronous
    writes (documented in docs/faq/env_var.md)."""
    from . import lib

    return getenv("MXNET_ENGINE_ASYNC_IO", "1") == "1" and \
        lib.native_engine() is not None


@contextlib.contextmanager
def bulk(size):
    """Parity `mx.engine.bulk`: a no-op scope on TPU — XLA whole-program
    compilation is the limit case of engine bulking (`threaded_engine.h:413`)."""
    yield


def path_var(path):
    """The per-path write variable: pushes naming the same path serialize
    (reference: one engine var per output resource)."""
    from . import lib

    eng = lib.native_engine()
    if eng is None:
        return None
    with _path_lock:
        v = _path_vars.get(path)
        if v is None:
            if len(_path_vars) >= _PATH_VAR_CAP:
                eng.wait_all()
                _var_pool.extend(_path_vars.values())
                _path_vars.clear()
            v = _path_vars[path] = (_var_pool.pop() if _var_pool
                                    else eng.new_var())
    return v


def _guarded(fn):
    def run(*a, **kw):
        try:
            fn(*a, **kw)
        except Exception as e:  # KeyboardInterrupt/SystemExit propagate
            if telemetry._enabled:
                telemetry.counter("engine.async_errors").inc()
            _async_error.append(e)

    return run


def _instrumented(fn):
    """Telemetry wrap for one pushed task: queue-depth gauge up at push /
    down at run, push→run latency histogram. The latency is how long work
    sat behind other engine tasks — the first number to look at when
    checkpoint writes stall an epoch."""
    t_push = time.perf_counter()
    g = telemetry.gauge("engine.queue_depth")
    h = telemetry.histogram("engine.push_run_latency_us")
    g.inc()

    def run(*a, **kw):
        h.record((time.perf_counter() - t_push) * 1e6)
        g.dec()
        return fn(*a, **kw)

    return run


def _traced(fn, name):
    """Tracing wrap for one pushed task: capture the pushing thread's span
    context, re-attach it on the engine worker, and draw the flow arrow —
    an async checkpoint write lands under the step that pushed it in the
    trace, on the worker's own timeline row."""
    carrier = tracing.inject()
    flow_id = None
    if carrier is not None:
        # flow start must sit inside an open slice on the pushing thread;
        # carrier != None means one exists (inject() found an open span)
        flow_id = tracing.new_flow_id()
        tracing.flow_start(flow_id, name=name)

    def run(*a, **kw):
        with tracing.attach(carrier):
            with tracing.span(name, cat="engine"):
                if flow_id is not None:
                    tracing.flow_end(flow_id, name=name)
                return fn(*a, **kw)

    return run


def push(fn, *args, const_vars=(), mutable_vars=(), **kwargs):
    """Push host-side async work onto the native engine (falls back to
    inline execution when the native library is unavailable)."""
    from . import lib

    if telemetry._enabled:
        telemetry.counter("engine.pushes").inc()
        fn = _instrumented(fn)
    if tracing._enabled:
        fn = _traced(fn, "engine.task")
    eng = lib.native_engine()
    if eng is not None:
        return eng.push(_guarded(fn), args, kwargs,
                        const_vars=const_vars, mutable_vars=mutable_vars)
    fn(*args, **kwargs)
    return None


def push_io(path, fn, *args, retries=None, **kwargs):
    """Push a file write ordered against other writes to `path`. The
    payload fn rides the resilience retry budget (jittered exponential
    backoff) so a transient EIO on an engine worker does not lose the
    write — `fn` must be idempotent (our writers are: temp file + atomic
    rename). `retries=0` opts out."""
    from . import resilience

    if telemetry._enabled:
        telemetry.counter("engine.io_pushes").inc()
    wrapped = resilience.wrap_retry(fn, desc=path, retries=retries)
    return push(wrapped, *args, mutable_vars=(path_var(path),), **kwargs)


def wait_all():
    from . import lib
    from .ndarray import waitall

    if analysis._enabled:
        # draining the engine blocks on worker threads: holding any
        # tracked lock here is a deadlock-in-waiting
        analysis.check_blocking("engine.wait_all")
    eng = lib.native_engine()
    if eng is not None:
        eng.wait_all()
    waitall()
    if _async_error:
        errs = list(_async_error)
        _async_error.clear()
        if len(errs) == 1:
            raise errs[0]
        raise ExceptionGroup("async engine IO failures", errs)


@atexit.register
def _flush_at_exit():
    """Pending async checkpoint writes must land before the process dies.
    Failures here are the WORST place to be silent — a final checkpoint
    that never hit disk — so they are logged to stderr, never swallowed
    (the reference engine aborts the process on an op error)."""
    from . import lib

    eng = lib._engine  # do not CREATE an engine at exit
    if eng is not None:
        try:
            eng.wait_all()
        except Exception as e:  # interpreter is dying; log, don't raise
            _log_exit_error(e)
    for e in _async_error:
        _log_exit_error(e)
    _async_error.clear()


def _log_exit_error(e):
    try:
        from .log import get_logger

        get_logger("mxnet_tpu.engine").error(
            "async IO failure pending at interpreter exit "
            "(a final checkpoint may be lost): %r", e)
    except Exception:  # logging machinery already torn down
        import sys

        try:
            sys.stderr.write(f"mxnet_tpu.engine: async IO failure at exit: {e!r}\n")
        except Exception:
            pass
