"""Network visualization (parity: `python/mxnet/visualization.py` —
print_summary + plot_network)."""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer-by-layer summary table of a Symbol graph."""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {e[0] for e in conf["heads"]}
    if shape is not None:
        _, out_shapes, _ = symbol.infer_shape(**shape)
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    for i, node in enumerate(nodes):
        op = node["op"]
        if op == "null" and i not in heads and not node["name"].endswith(("weight", "bias", "gamma", "beta")):
            continue
        pre = ",".join(nodes[e[0]]["name"] for e in node.get("inputs", []))
        print_row([f"{node['name']} ({op})", "", "", pre], positions)
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot; requires the `graphviz` python package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires graphviz") from e
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null" and hide_weights and name.endswith(("weight", "bias", "gamma", "beta",
                                                            "moving_mean", "moving_var")):
            continue
        dot.node(name=name, label=f"{name}\n{op}" if op != "null" else name, shape="box")
        for e in node.get("inputs", []):
            src = nodes[e[0]]["name"]
            if hide_weights and nodes[e[0]]["op"] == "null" and src.endswith(
                    ("weight", "bias", "gamma", "beta", "moving_mean", "moving_var")):
                continue
            dot.edge(src, name)
    return dot
