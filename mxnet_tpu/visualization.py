"""Network visualization (parity: `python/mxnet/visualization.py` —
print_summary + plot_network)."""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer-by-layer summary table of a Symbol graph, with output
    shapes and parameter counts when input `shape`s are given (reference
    visualization.py print_summary)."""
    import numpy as _np

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {e[0] for e in conf["heads"]}
    arg_shapes = {}
    out_shape_of = {}
    if shape is not None:
        internals = symbol.get_internals()
        a_sh, o_sh, x_sh = internals.infer_shape_partial(**shape)
        arg_names = internals.list_arguments()
        aux_names = internals.list_auxiliary_states()
        arg_shapes = {n: s for n, s in zip(arg_names, a_sh)}
        arg_shapes.update({n: s for n, s in zip(aux_names, x_sh)})
        for name, s in zip(internals.list_outputs(), o_sh):
            out_shape_of[name] = s
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    def node_out_shape(node):
        for suffix in ("_output", ""):
            s = out_shape_of.get(node["name"] + suffix)
            if s is not None:
                return s
        return out_shape_of.get(node["name"] + "_output0")

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    seen_params = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        if op == "null":
            continue
        # parameters feeding this op node (null inputs that aren't data)
        n_params = 0
        pre_list = []
        for e in node.get("inputs", []):
            src = nodes[e[0]]
            if src["op"] == "null":
                if shape is not None and src["name"] in arg_shapes and \
                        src["name"] not in shape and src["name"] not in seen_params:
                    s = arg_shapes[src["name"]]
                    if s is not None:
                        n_params += int(_np.prod(s))
                    seen_params.add(src["name"])
            else:
                pre_list.append(src["name"])
        total_params += n_params
        out_s = node_out_shape(node) if shape is not None else ""
        print_row([f"{node['name']} ({op})", str(out_s or ""), n_params,
                   ",".join(pre_list)], positions)
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot; requires the `graphviz` python package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires graphviz") from e
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null" and hide_weights and name.endswith(("weight", "bias", "gamma", "beta",
                                                            "moving_mean", "moving_var")):
            continue
        dot.node(name=name, label=f"{name}\n{op}" if op != "null" else name, shape="box")
        for e in node.get("inputs", []):
            src = nodes[e[0]]["name"]
            if hide_weights and nodes[e[0]]["op"] == "null" and src.endswith(
                    ("weight", "bias", "gamma", "beta", "moving_mean", "moving_var")):
                continue
            dot.edge(src, name)
    return dot
