"""Data iterators (parity: `python/mxnet/io/`)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, MNISTIter, LibSVMIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "LibSVMIter"]
