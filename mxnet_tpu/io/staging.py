"""Double-buffered device staging — the host-overlap half of the async
dispatch pipeline.

The reference's L2 dependency engine (`include/mxnet/engine.h`) exists to
hide host work behind device compute; on the TPU-native port the same gap
shows up as ``host_gap_us`` (wall − exec) in the observatory: every
lockstep step pays batch pad/cast/``device_put`` and metric reads on the
critical path while the device sits idle.  :class:`DeviceStager` closes
the input half of that gap: while step *t* executes, a staging thread
pads/casts/places batch *t+1* into a bounded ring of pre-placed buffers,
so the consuming step finds device-resident arrays instead of host
numpy.  The consumer side (``Module.fit``'s deferred metric lane, the
serving batcher's stage-ahead, the generation tick's
dispatch-then-bookkeep reorder) lives with each loop; this module owns
only the buffer discipline.

Correctness invariants, in order of importance:

* **Donation safety** — a staged slot's arrays stay strongly referenced
  from :meth:`DeviceStager.stage` until :meth:`DeviceStager.retire`, and
  ``stage`` refuses new work while every slot is staged or in flight.
  Feeds are never donated by the fused program (see
  ``Executor.fused_step``'s donate tuple), but the ring discipline is
  what guarantees a buffer is not recycled by the allocator while the
  step consuming it is still in flight.
* **Identity hand-off** — :meth:`DeviceStager.take` matches on the batch
  *object*, not its contents; a consumer that shows up with a different
  batch (reordered iterator, bucketing switch) simply misses and falls
  back to the lockstep path.  Staging is an optimisation, never a
  semantic.
* **Lock coverage** — the ring's condition comes from
  ``analysis.make_condition``, so ``MXNET_DEBUG_SYNC=1`` folds the
  staging thread into the lock-order/blocking-hazard analysis like every
  other subsystem.

``MXNET_OVERLAP=0`` disables every overlap lane at once (fit, serving,
generation) and restores bit-exact lockstep — the reference semantics the
parity tests pin against.  ``MXNET_STAGING_BUFFERS`` sizes the ring
(default 2 = classic double buffering: one in flight, one staging).
"""
from __future__ import annotations

import threading
import time as _time

from .. import telemetry
from ..base import getenv, register_env

register_env("MXNET_OVERLAP", 1,
             "async dispatch pipeline: overlap host work (batch staging, "
             "deferred metric reads, serving/generation bookkeeping) with "
             "in-flight device execution; 0 = bit-exact lockstep reference")
register_env("MXNET_STAGING_BUFFERS", 2,
             "DeviceStager ring depth: staged-but-unretired batches the "
             "input pipeline may hold on device (min 2 = double buffer)")


def overlap_enabled():
    """One switch for every overlap lane (fit / serving / generation)."""
    return bool(int(getenv("MXNET_OVERLAP") or 0))


class _Slot:
    """One ring entry: the batch it was staged for, the prepared feed,
    and its lifecycle bits (ready -> in_flight -> retired)."""

    __slots__ = ("batch", "prep", "guard", "feed", "pad", "error",
                 "ready", "in_flight")

    def __init__(self, batch, prep, guard):
        self.batch = batch
        self.prep = prep
        self.guard = guard
        self.feed = None
        self.pad = 0
        self.error = None
        self.ready = False
        self.in_flight = False


class DeviceStager:
    """Bounded ring of device-staged input batches fed by one thread.

    Protocol (all methods are main-thread unless noted)::

        staged = stager.stage(batch, prep)   # enqueue; thread runs prep()
        ...dispatch step t...
        hit = stager.take(batch)             # (feed, pad) or None
        ...step consuming the feed completes (metric applied)...
        stager.retire()                      # oldest in-flight slot freed

    ``prep`` runs on the staging thread and returns ``(feed_dict, pad)``
    where the feed values are already cast + device-placed (honoring the
    caller's SPMD input shardings).  ``guard`` (optional) is re-checked at
    ``take`` time on the main thread; returning False discards the slot —
    the consumer's placement context changed between stage and consume.
    """

    def __init__(self, name="io.stager", depth=None):
        if depth is None:
            depth = int(getenv("MXNET_STAGING_BUFFERS") or 2)
        self._depth = max(2, int(depth))
        # analysis-tracked so MXNET_DEBUG_SYNC sees the staging thread
        from .. import analysis
        self._cv = analysis.make_condition(name)
        self._name = name
        self._slots = []          # FIFO: staged + in-flight, oldest first
        self._queue = []          # staged-but-unprepared, thread input
        self._thread = None
        self._closed = False

    # -- introspection (tests pin the donation-safety discipline on these)
    @property
    def depth(self):
        return self._depth

    def occupancy(self):
        """(staged_or_preparing, in_flight) slot counts."""
        with self._cv:
            live = [s for s in self._slots]
            return (len([s for s in live if not s.in_flight]),
                    len([s for s in live if s.in_flight]))

    # -- producer side -----------------------------------------------------
    def stage(self, batch, prep, guard=None, block=False):
        """Enqueue ``batch`` for staging; returns True if accepted.

        When the ring is full (every slot staged or in flight — i.e. the
        consumer is behind by ``depth`` steps), the batch is NOT staged
        and False is returned unless ``block``: dropping to lockstep for
        one step is always safe, silently reusing a live buffer never is.
        """
        if self._closed:
            return False
        with self._cv:
            if block:
                while len(self._slots) >= self._depth and not self._closed:
                    self._cv.wait(timeout=0.05)
            if self._closed or len(self._slots) >= self._depth:
                telemetry.counter("io.stage_ring_full").inc()
                return False
            slot = _Slot(batch, prep, guard)
            self._slots.append(slot)
            self._queue.append(slot)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread.start()
            self._cv.notify_all()
        return True

    # -- staging thread ----------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(timeout=0.2)
                if self._closed and not self._queue:
                    return
                slot = self._queue.pop(0)
            t0 = _time.perf_counter()
            try:
                slot_feed, slot_pad = slot.prep()
            except Exception as e:  # consumer falls back to lockstep
                slot.error = e
                slot_feed, slot_pad = None, 0
            dt_us = (_time.perf_counter() - t0) * 1e6
            telemetry.counter("io.stage_prep_us_total").inc(int(dt_us))
            with self._cv:
                slot.feed, slot.pad = slot_feed, slot_pad
                slot.ready = True
                self._cv.notify_all()

    # -- consumer side -----------------------------------------------------
    def take(self, batch):
        """The staged ``(feed, pad)`` for this exact batch object, or None.

        Blocks (counted into ``io.stage_wait_us_total``) if the staging
        thread has not finished preparing it yet; a miss, a prep error, or
        a failed ``guard`` re-check all return None and drop the slot so
        the caller runs its lockstep path.
        """
        with self._cv:
            slot = None
            for s in self._slots:
                if not s.in_flight and s.batch is batch:
                    slot = s
                    break
            if slot is None:
                return None
            t0 = _time.perf_counter()
            waited = False
            while not slot.ready:
                waited = True
                self._cv.wait(timeout=0.2)
            if waited:
                telemetry.counter("io.stage_wait_us_total").inc(
                    int((_time.perf_counter() - t0) * 1e6))
            if slot.error is not None or slot.feed is None or \
                    (slot.guard is not None and not slot.guard()):
                self._slots.remove(slot)
                self._cv.notify_all()
                telemetry.counter("overlap.fallback_batches").inc()
                return None
            slot.in_flight = True
            telemetry.counter("overlap.staged_batches").inc()
            return slot.feed, slot.pad

    def retire(self):
        """Free the oldest in-flight slot — call once the step that
        consumed it can no longer be touching its buffers (its outputs
        were read, or a later step completed).  Idempotent when nothing
        is in flight."""
        with self._cv:
            for i, s in enumerate(self._slots):
                if s.in_flight:
                    del self._slots[i]
                    self._cv.notify_all()
                    return True
            return False

    def close(self):
        """Drop every slot and stop the staging thread (fit teardown)."""
        with self._cv:
            self._closed = True
            self._queue = []
            self._slots = []
            self._cv.notify_all()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=2.0)
