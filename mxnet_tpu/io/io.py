"""Data iterators — the python I/O layer.

Parity: `python/mxnet/io/io.py` (`DataIter`, `DataBatch`, `DataDesc`,
`NDArrayIter`, `ResizeIter`, `PrefetchingIter`) plus python-native
renderings of the C++ registered iterators the reference implements in
`src/io/` (`MNISTIter` `iter_mnist.cc:260`, `CSVIter` `iter_csv.cc:218`,
`LibSVMIter` `iter_libsvm.cc:200`).

TPU-native notes: batches are host numpy until they reach an executor —
the device transfer happens once per batch at the jit boundary, matching
the reference's copy-to-ctx in `BatchLoader`/`PrefetcherIter`. The
prefetcher here is a background thread pipelining host-side batch prep
against device compute (the role of `iter_prefetcher.h`).
"""
from __future__ import annotations

import collections
import threading
import time as _time
import queue as _queue

import numpy as _np

from .. import telemetry
from .. import tracing
from ..base import MXNetError


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape (+dtype/layout) of one input stream."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        types = dict(types) if types else {}
        return [DataDesc(n, s, types.get(n, _np.float32)) for n, s in shapes]


class DataBatch:
    """One minibatch: lists of data/label NDArrays + padding metadata."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise TypeError("Data must be list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise TypeError("Label must be list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        dshapes = [d.shape for d in self.data] if self.data else []
        lshapes = [l.shape for l in self.label] if self.label else []
        return f"{type(self).__name__}: data shapes: {dshapes} label shapes: {lshapes}"


# (rows, batch_size) -> device index vector mapping a short batch onto its
# padded bucket (row i<n keeps data[i], row n+j recycles data[j % n]).  One
# gather with a cached index replaces the per-call concatenate chain, so a
# partial batch costs zero fresh host allocations on the hot path; LRU keeps
# the cache bounded across pathological shape churn.  ``_pad_index`` is the
# preallocated per-bucket pad buffer — tests pin its id-stability.
_PAD_INDEX_CACHE = collections.OrderedDict()
_PAD_INDEX_CACHE_MAX = 64


def _pad_index(n, batch_size):
    """Cached wrap-around gather index for padding ``n`` rows up to
    ``batch_size``; the same (n, batch_size) returns the SAME array."""
    import jax.numpy as jnp

    key = (int(n), int(batch_size))
    idx = _PAD_INDEX_CACHE.get(key)
    if idx is None:
        pad = batch_size - n
        idx = jnp.asarray(
            _np.concatenate([_np.arange(n), _np.arange(pad) % n]).astype(
                _np.int32))
        _PAD_INDEX_CACHE[key] = idx
        while len(_PAD_INDEX_CACHE) > _PAD_INDEX_CACHE_MAX:
            _PAD_INDEX_CACHE.popitem(last=False)
    else:
        _PAD_INDEX_CACHE.move_to_end(key)
    return idx


def pad_arrays(arrays, batch_size):
    """Pad each array in ``arrays`` along axis 0 up to ``batch_size`` by
    recycling its rows from the start (wrapping around if the batch is
    shorter than the pad); returns ``(padded_list, pad)``.

    This is the shape-stability half of the partial-last-batch story: a
    short final batch padded up to the bound batch size reuses the already
    compiled executable (one compile-cache entry per bucket), where
    rebinding/reshaping the executor would recompile every epoch. The
    consumer (``Module``) slices outputs and metric updates back down by
    ``pad`` rows, so the padding never leaks into results. Padded rows DO
    ride through the gradient — the same semantics as `NDArrayIter`'s
    ``last_batch_handle='pad'`` (reference io.py), which likewise recycles
    distinct samples into the tail batch and trains on them (recycling,
    rather than repeating one row, keeps the duplication spread evenly).
    """
    import jax.numpy as jnp

    from ..ndarray import NDArray

    out, pad = [], 0
    for a in arrays:
        n = a.shape[0]
        if n >= batch_size:
            out.append(a)
            continue
        if n == 0:
            raise MXNetError("pad_arrays: cannot pad an empty batch "
                             "(no rows to recycle)")
        pad = batch_size - n
        data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
        out.append(NDArray(jnp.take(data, _pad_index(n, batch_size), axis=0)))
    return out, pad


class DataIter:
    """Iterator base (reference io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize array/list/dict input to a list of (name, numpy) pairs."""
    from ..ndarray import NDArray

    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError(f"{default_name} must be non-empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError(f"Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py NDArrayIter): supports
    dict/list/single data+label, shuffling, and last-batch handling
    ('pad' | 'discard' | 'roll_over')."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise ValueError(f"{k} has {v.shape[0]} rows, expected {self.num_data}")
        if last_batch_handle == "discard":
            if self.num_data < batch_size:
                raise MXNetError("not enough data for even one batch")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = _np.arange(self.num_data)
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
            self._idx_identity = False
        else:
            self._idx_identity = True
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        from ..ndarray import array as nd_array

        start = self.cursor
        end = min(start + self.batch_size, self.num_data)
        identity = getattr(self, "_idx_identity", False)
        out = []
        for k, v in arrays:
            if start >= 0:
                if identity:
                    # unshuffled: a plain slice view — the device transfer
                    # in nd_array is the only copy (fancy indexing would
                    # make a host copy first, once per array per batch)
                    chunk = v[start:end]
                else:
                    chunk = v[self.idx[start:end]]
            else:  # roll_over wrapped batch
                chunk = v[self.idx[start:]] if start < 0 else v[0:0]
                chunk = _np.concatenate([chunk, v[self.idx[:end]]]) if end > 0 else chunk
            if chunk.shape[0] < self.batch_size:  # pad from the front
                pad = self.batch_size - chunk.shape[0]
                chunk = _np.concatenate([chunk, v[self.idx[:pad]]])
            out.append(nd_array(chunk))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background prefetch over one or more iterators (the role of the
    reference's `PrefetcherIter`, `src/io/iter_prefetcher.h`).

    When the native runtime is built, batch fetches are PUSHED onto the
    native dependency engine (`src/engine.cc`) with one mutable var per
    prefetcher — fetches serialize in push order on an engine worker
    thread while the trainer consumes from the queue, exactly the
    reference's engine-scheduled IO pattern. Python-thread fallback
    otherwise."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, use_engine=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._name = (f"PrefetchingIter#{id(self):x}"
                      f"({','.join(type(i).__name__ for i in iters)})")
        from .. import lib

        # use_engine: None = native engine when built, False = force the
        # python-thread fallback, True = require the native engine
        self._engine = lib.native_engine() if use_engine in (None, True) else None
        if use_engine and self._engine is None:
            raise MXNetError("native engine requested but librt_tpu.so is not built")
        self._var = self._engine.new_var() if self._engine is not None else None
        self._epoch = 0
        self._handoff = None
        self._t_consumed = None  # end of the previous next() (telemetry)
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _fetch_one(self):
        """Fetch one combined batch (runs on an engine worker or the
        fallback thread); returns DataBatch | None (end) | Exception."""
        try:
            batches = []
            try:
                for it in self.iters:
                    batches.append(it.next())
            except StopIteration:
                return None
            data = sum([b.data for b in batches], [])
            label = sum([(b.label or []) for b in batches], [])
            return DataBatch(data=data, label=label, pad=batches[0].pad,
                             index=batches[0].index)
        except Exception as e:  # surface worker errors to the consumer
            return e

    def _push_fetch(self):
        """One engine-scheduled fetch; the per-prefetcher var orders it
        after every previously pushed fetch."""
        from .. import engine

        epoch = self._epoch
        q = self._queue

        def task():
            if epoch != self._epoch:
                return  # stale push from before a reset
            q.put(self._fetch_one())

        engine.push(task, mutable_vars=(self._var,))

    def _start(self):
        self._queue = _queue.Queue(maxsize=max(1, self._depth))
        self._stop = threading.Event()
        if self._engine is not None:
            self._thread = None
            self._done = False
            for _ in range(max(1, self._depth)):
                self._push_fetch()
            return

        # q/stop are bound per epoch: a thread wedged across a reset keeps
        # talking to ITS queue and ITS (already set) stop event, never the
        # replacement epoch's. `handoff` is a predecessor that outlived its
        # join timeout: the new worker waits it out (and only then resets
        # the sources) so two threads never touch the source iters at once.
        def worker(q=self._queue, stop=self._stop, prev=self._handoff):
            from .. import resilience

            if prev is not None:
                prev.join()
                for it in self.iters:
                    it.reset()
            while not stop.is_set():
                try:
                    resilience.inject("prefetch", self._name)
                except resilience.ThreadKilled:
                    return  # simulated silent thread death
                # the span puts the prefetch thread's fetch windows on its
                # own trace row (the engine path gets this — plus consumer
                # parenting — through engine.push's inject/attach)
                with tracing.span("io.prefetch_fetch", cat="io"):
                    item = self._fetch_one()
                q.put(item)
                if item is None or isinstance(item, Exception):
                    return

        self._handoff = None
        self._thread = threading.Thread(target=worker, daemon=True,
                                        name=self._name)
        self._thread.start()

    def reset(self):
        from ..base import getenv
        from ..log import get_logger

        self._stop.set()
        self._epoch += 1  # stale engine pushes become no-ops
        # the inter-epoch gap (validation, checkpointing, user code) is not
        # step compute — counting it would understate the starvation ratio
        self._t_consumed = None
        if self._engine is not None:
            from .. import engine

            engine.wait_all()  # drain in-flight fetches before reusing iters
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        stale = None
        if self._thread is not None:
            timeout = float(getenv("MXNET_PREFETCH_JOIN_TIMEOUT"))
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # a wedged fetch (hung filesystem, deadlocked source iter)
                # cannot be killed from python — abandon the daemon thread
                # but never silently: the epoch it blocks is lost work
                get_logger("mxnet_tpu.io").warning(
                    "%s: prefetch thread still alive %.1fs after reset(); "
                    "new epoch is deferred until it exits (source iterator "
                    "may be wedged)", self._name, timeout)
                stale = self._thread
        self._handoff = stale
        if stale is None:
            for it in self.iters:
                it.reset()
        # else: the replacement worker joins the stale thread and resets
        # the sources itself — two threads must never share the iters
        self._start()

    def next(self):
        if self._engine is not None and self._done:
            raise StopIteration
        if telemetry._enabled:
            # data-wait vs. compute split: wait is the time blocked on the
            # queue here; compute is the gap since the previous batch was
            # handed out (the consumer's fwd/bwd/update work). Their ratio
            # wait/(wait+compute) is the starvation ratio — the pipeline is
            # data-bound when it climbs toward 1 (docs/faq/perf.md).
            t0 = _time.perf_counter()
            if self._t_consumed is not None:
                telemetry.counter("io.prefetch_compute_us_total").inc(
                    (t0 - self._t_consumed) * 1e6)
            item = self._get_item()
            wait_us = (_time.perf_counter() - t0) * 1e6
            telemetry.histogram("io.prefetch_wait_us").record(wait_us)
            telemetry.counter("io.prefetch_wait_us_total").inc(wait_us)
            self._t_consumed = _time.perf_counter()
        else:
            item = self._get_item()
        if item is None:
            if self._engine is not None:
                self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        if self._engine is not None and not self._done:
            self._push_fetch()  # keep the pipeline `depth` deep
        return item

    def _get_item(self):
        """Blocking queue read that cannot hang forever on a dead producer:
        the python-thread path polls worker liveness, so a prefetch thread
        that dies without delivering (kill injection, interpreter bug)
        surfaces as MXNetError instead of a wedged training loop."""
        if self._thread is None:
            return self._queue.get()
        while True:
            try:
                return self._queue.get(timeout=0.1)
            except _queue.Empty:
                if self._thread.is_alive():
                    continue
                try:
                    # the final put may have raced the liveness check
                    return self._queue.get_nowait()
                except _queue.Empty:
                    raise MXNetError(
                        f"{self._name}: prefetch thread died without "
                        "delivering a batch") from None

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False


class CSVIter(NDArrayIter):
    """CSV file iterator (reference `iter_csv.cc:218`), python-native:
    loads the csv once and batches in memory."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard",
                         label_name="label")


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (reference `iter_mnist.cc:260`)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, silent=False, input_shape=None, **kwargs):
        import gzip
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = struct.unpack(">I", f.read(4))[0]
                ndim = magic & 0xFF
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

        images = read_idx(image).astype("float32") / 255.0
        labels = read_idx(label).astype("float32")
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        super().__init__(images, labels, batch_size=batch_size,
                         shuffle=shuffle, label_name="label")


class LibSVMIter(NDArrayIter):
    """LibSVM-format iterator (reference `iter_libsvm.cc:200`), dense-backed:
    rows parse to dense feature vectors of `data_shape`."""

    def __init__(self, data_libsvm, data_shape, label_shape=None,
                 batch_size=1, **kwargs):
        dim = int(_np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                vec = _np.zeros(dim, dtype=_np.float32)
                for tok in parts[1:]:
                    i, _, v = tok.partition(":")
                    vec[int(i)] = float(v)
                rows.append(vec)
        data = _np.stack(rows).reshape((-1,) + tuple(data_shape))
        super().__init__(data, _np.asarray(labels, dtype=_np.float32),
                         batch_size=batch_size, label_name="label")
