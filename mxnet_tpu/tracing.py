"""Span tracing: per-request / per-step causality across threads and queues.

PR 2's telemetry answers *how much* (p99 latency, counters); this layer
answers *which one and where*: every serving request and every training
step becomes a tree of spans — admission wait → queue → pad → execute →
reassembly for a request, data → fwd/bwd → grad-sync → update → sync for a
step — stitched across the thread and queue handoffs the runtime makes
(batcher worker, caller-runs assist, prefetch thread, engine push).

Concepts (OpenTelemetry-shaped, chrome-trace rendered):

* a **trace** is one causal unit (one request, one step) identified by a
  16-hex ``trace_id``. Dist runs derive step trace ids DETERMINISTICALLY
  from ``(tag, epoch, step)`` (:func:`deterministic_trace_id`) so every
  worker labels the same step with the same id without communicating —
  ``tools/trace_merge.py`` joins per-worker dumps on exactly this.
* a **span** is one timed stage inside a trace, with a ``parent_id`` link.
  Spans propagate through a :mod:`contextvars` context var, so nested
  ``span()`` scopes parent automatically *within* a thread; crossing a
  thread/queue boundary is explicit — :func:`inject` captures the current
  context into a plain dict carried with the work item, and
  :func:`attach` re-establishes it on the far side (the batcher's Request,
  ``engine.push`` tasks and the prefetch thread all do this).
* **flow events** (:func:`flow_start` / :func:`flow_end`) draw the
  cross-thread arrow in chrome://tracing / perfetto from the span that
  enqueued work to the span that ran it (a request's root → the batch
  that computed it).

Export: spans are chrome-trace complete (``"X"``) events carrying
``trace_id``/``span_id``/``parent_id`` in ``args``, buffered here
(bounded, drops counted) and merged into ``profiler.dump()`` — one trace
file shows host spans, op dispatch, telemetry counters and cross-thread
request flows on a single timeline.

The **flight recorder** keeps the full span tree of the worst (slowest)
training step seen since it was last read: when the p99 regresses, the
answer to "what did the slow step actually do" is one
:func:`flight_recorder.worst` call away (``BaseModule.fit`` feeds it,
``Speedometer`` reads it per log tick, the telemetry HTTP endpoint serves
it under ``/trace``).

Overhead discipline: like telemetry, everything gates on the module-level
``_enabled`` flag (``MXNET_TRACING=1`` or :func:`enable`); instrumented
call sites check it before taking any timestamp, so the fused hot path
pays one attribute read per site when tracing is off
(``test_tracing.py`` pins the disabled path emitting nothing).
"""
from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import random
import threading
import time

from . import analysis
from .base import getenv, register_env

__all__ = ["Span", "span", "emit_span", "begin", "inject", "attach",
           "current", "flow_start", "flow_end", "new_flow_id",
           "deterministic_trace_id",
           "enabled", "enable", "disable", "take_events", "peek_events",
           "dropped_events", "reset", "FlightRecorder", "flight_recorder",
           "tick_recorder", "now_us"]

register_env("MXNET_TRACING", False,
             "enable span tracing (per-request / per-step span trees "
             "merged into profiler.dump())")
register_env("MXNET_TRACING_MAX_EVENTS", 1 << 19,
             "span event buffer cap; overflow counts into "
             "tracing.dropped_events()")

# memoized buffer cap — _push() runs under the global lock on every
# event, so it must not re-parse the environment there; keying the memo
# on the raw env string keeps runtime changes honored at the cost of one
# dict lookup per event. The sentinel first entry (False is never a raw
# env value) defers the first parse to first use — import stays
# side-effect-free (tpulint gate-discipline)
_max_memo = (False, 0)


def _max_events():
    global _max_memo
    raw = os.environ.get("MXNET_TRACING_MAX_EVENTS")
    if raw != _max_memo[0]:
        _max_memo = (raw, int(getenv("MXNET_TRACING_MAX_EVENTS")))
    return _max_memo[1]

# THE gate — call sites read `tracing._enabled` (one attribute fetch)
# before any other tracing work, including timestamps.
_enabled = bool(getenv("MXNET_TRACING"))

# context value: the innermost open Span, or a _RemoteCtx re-attached from
# an inject() carrier. Both expose .trace_id / .span_id; only a local open
# Span collects finished-child records (the flight-recorder tree).
_ctx = contextvars.ContextVar("mxnet_tpu_trace", default=None)

_events = []
_dropped = 0
_unmirrored = 0  # drops not yet flushed into the telemetry counter
_lock = analysis.make_lock("tracing.events")
_rand = random.Random()


def now_us():
    """Wall-clock microseconds — the SAME timebase as profiler events, so
    spans and op dispatch line up on one chrome-trace timeline."""
    return time.time() * 1e6


def _new_id():
    return f"{_rand.getrandbits(64):016x}"


def deterministic_trace_id(*parts):
    """A trace id every worker of a dist run computes identically from the
    same logical coordinates (e.g. ``("fit", epoch, step)``) — the join key
    ``tools/trace_merge.py`` uses to connect per-worker dumps without any
    cross-process id exchange."""
    h = hashlib.md5(repr(parts).encode()).hexdigest()
    return h[:16]


def enabled():
    return _enabled


def enable(on=True):
    """Turn span tracing on (also: ``MXNET_TRACING=1`` at import)."""
    global _enabled
    _enabled = bool(on)


def disable():
    enable(False)


def reset():
    """Drop buffered events and the flight recorder (tests)."""
    global _dropped, _unmirrored
    with _lock:
        _events.clear()
        _dropped = 0
        _unmirrored = 0
    flight_recorder.reset()
    tick_recorder.reset()


def dropped_events():
    """Span events discarded because the buffer was full."""
    return _dropped


def _push(ev):
    global _dropped, _unmirrored
    with _lock:
        # once the buffer is full the drop path IS the steady state:
        # only count here, flush into the telemetry counter at capture
        # time (take_events) so no per-event registry-lock take
        if len(_events) >= _max_events():
            _dropped += 1
            _unmirrored += 1
            return
        _events.append(ev)


def take_events(reset=False):
    """Snapshot ``(events, dropped)``; ``reset`` drains in the same
    critical section (profiler._capture merges through this so a span is
    in exactly one dump). Flushes accumulated drops into the monotonic
    ``tracing.dropped_events`` telemetry counter."""
    global _dropped, _unmirrored
    with _lock:
        events = list(_events)
        dropped = _dropped
        mirror = _unmirrored
        _unmirrored = 0
        if reset:
            _events.clear()
            _dropped = 0
    if mirror:
        try:  # mirror into the metrics plane, like profiler drops
            from . import telemetry

            telemetry.counter("tracing.dropped_events").inc(mirror)
        except Exception:  # noqa: BLE001
            pass
    return events, dropped


def peek_events():
    return take_events(reset=False)[0]


class _RemoteCtx:
    """A context re-attached from an inject() carrier: parent linkage
    only, no local open Span to collect children into."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    """One timed stage. Use the :func:`span` context manager for the
    common in-thread case; :func:`begin` + :meth:`finish` for spans whose
    start and end live on different threads (a serving request's root)."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "t0", "args", "children", "_token", "record",
                 "pid", "tid")

    def __init__(self, name, cat="host", trace_id=None, parent=None,
                 args=None):
        self.name = name
        self.cat = cat
        # lane identity is where the span BEGAN: a cross-thread root
        # (begun on the submitting client thread, finished by the batcher
        # worker) must render on the client's lane — stamping the finisher
        # would pile every concurrent request root onto the worker's lane
        # as overlapping, non-nestable slices
        self.pid = os.getpid()
        self.tid = threading.get_ident()
        if parent is None:
            parent = _ctx.get()
            # an explicit trace_id that DIFFERS from the ambient context's
            # starts a new trace (a deterministic step id under a
            # user-opened outer span): keep no parent link, or the merge
            # audit would flag every such span as a cross-trace orphan.
            # An explicitly-passed parent is kept as given.
            if (parent is not None and trace_id
                    and parent.trace_id != trace_id):
                parent = None
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = (trace_id or
                         (parent.trace_id if parent is not None else None)
                         or _new_id())
        self.span_id = _new_id()
        self.t0 = now_us()
        self.args = dict(args) if args else {}
        self.children = []   # finished child records (flight-recorder tree)
        self._token = None
        self.record = None   # set by finish()

    # -- context-manager use (same-thread begin/end) -------------------------

    def __enter__(self):
        self._token = _ctx.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _ctx.reset(self._token)
            self._token = None
        if exc is not None:
            self.args.setdefault("error", repr(exc))
        self.finish()
        return False

    # -- explicit lifecycle (cross-thread spans) -----------------------------

    def set(self, **kwargs):
        """Attach extra args to the span (rendered in the trace viewer)."""
        self.args.update(kwargs)
        return self

    def child(self, name, cat=None, args=None):
        """An explicitly-parented child (for cross-thread trees where the
        contextvar does not carry this span)."""
        return Span(name, cat or self.cat, parent=self, args=args)

    def finish(self, ts=None, dur=None):
        """Emit the chrome-trace complete event (idempotent). ``ts``/
        ``dur`` (us) override the measured window — used for spans
        reconstructed after the fact from recorded timestamps."""
        if self.record is not None:
            return self.record
        t0 = self.t0 if ts is None else ts
        d = (now_us() - t0) if dur is None else dur
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        args.update(self.args)
        self.record = {"name": self.name, "ph": "X", "cat": self.cat,
                       "pid": self.pid, "tid": self.tid,
                       "ts": t0, "dur": d, "args": args}
        if self.children:
            # the flight-recorder tree rides on the record, NOT into the
            # chrome event (viewers reconstruct nesting from ts/dur)
            self.record = dict(self.record, children=self.children)
        _push({k: v for k, v in self.record.items() if k != "children"})
        parent = _ctx.get()
        if isinstance(parent, Span) and parent.span_id == self.parent_id:
            parent.children.append(self.tree())
        return self.record

    def tree(self):
        """The finished span as a nested dict (children included) — the
        flight-recorder / HTTP representation."""
        rec = self.record or {}
        out = {"name": self.name, "cat": self.cat, "ts": rec.get("ts"),
               "dur": rec.get("dur"), "trace_id": self.trace_id,
               "span_id": self.span_id, "parent_id": self.parent_id,
               "args": dict(self.args)}
        if self.children:
            out["children"] = list(self.children)
        return out

    def adopt(self, child_tree):
        """Graft an externally-built child record onto this (still open)
        span's tree (cross-thread children that finished elsewhere)."""
        self.children.append(child_tree)


class _NullSpan:
    """The disabled path: one shared, stateless object — entering it,
    setting args on it and finishing it are all no-ops."""

    __slots__ = ()
    trace_id = None
    span_id = None
    children = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def set(self, **kwargs):
        return self

    def child(self, name, cat=None, args=None):
        return self

    def finish(self, ts=None, dur=None):
        return None

    def tree(self):
        return None

    def adopt(self, child_tree):
        pass


_NULL = _NullSpan()


def span(name, cat="host", trace_id=None, **args):
    """Context manager for one in-thread span, parented to the current
    context. Returns a shared no-op when tracing is off."""
    if not _enabled:
        return _NULL
    return Span(name, cat, trace_id=trace_id, args=args)


def begin(name, cat="host", trace_id=None, parent=None, **args):
    """Start a span WITHOUT entering the context var — for spans finished
    on another thread (:meth:`Span.finish`). No-op span when off."""
    if not _enabled:
        return _NULL
    return Span(name, cat, trace_id=trace_id, parent=parent, args=args)


def emit_span(name, t0_us, dur_us, cat="host", parent=None, trace_id=None,
              **args):
    """Emit a complete span after the fact from recorded timestamps —
    the spelling for hot loops that mark boundaries cheaply and
    reconstruct the tree once per step. Returns the span's tree record."""
    if not _enabled:
        return None
    sp = Span(name, cat, trace_id=trace_id, parent=parent, args=args)
    sp.t0 = t0_us
    return sp.finish(ts=t0_us, dur=dur_us)


def current():
    """The innermost open span (or re-attached remote context), or None."""
    return _ctx.get()


def inject():
    """Capture the current context as a plain dict to carry across a
    thread/queue boundary (None when off or no context)."""
    if not _enabled:
        return None
    cur = _ctx.get()
    if cur is None:
        return None
    return {"trace_id": cur.trace_id, "span_id": cur.span_id}


@contextlib.contextmanager
def attach(carrier):
    """Re-establish an injected context on the receiving thread: spans
    opened inside parent to the carrier's span. ``None`` carriers (tracing
    off at inject time) attach nothing."""
    if carrier is None or not _enabled:
        yield None
        return
    if isinstance(carrier, (Span, _RemoteCtx)):
        ctx = carrier
    else:
        ctx = _RemoteCtx(carrier["trace_id"], carrier["span_id"])
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


def new_flow_id():
    """A fresh id for one flow arrow (the same id must be passed to both
    :func:`flow_start` and :func:`flow_end`)."""
    return _new_id()


def flow_start(flow_id, name="flow", cat="flow"):
    """Chrome-trace flow-start (``"s"``): the enqueue side of a
    cross-thread arrow. Must be emitted from within a duration event's
    window on this thread (i.e. inside an open span)."""
    if not _enabled:
        return
    _push({"name": name, "ph": "s", "cat": cat, "id": flow_id,
           "pid": os.getpid(), "tid": threading.get_ident(),
           "ts": now_us()})


def flow_end(flow_id, name="flow", cat="flow"):
    """Chrome-trace flow-end (``"f"``, binding point enclosing slice):
    the execute side of the arrow."""
    if not _enabled:
        return
    _push({"name": name, "ph": "f", "cat": cat, "id": flow_id, "bp": "e",
           "pid": os.getpid(), "tid": threading.get_ident(),
           "ts": now_us()})


class FlightRecorder:
    """Keeps the worst (longest-duration) span tree observed since the
    last read — the slow-step black box. ``BaseModule.fit`` observes every
    step's root span; ``Speedometer`` reads (and resets) per log
    interval; :func:`worst` without reset is the on-demand dump (HTTP
    ``/trace`` serves it)."""

    def __init__(self):
        self._lock = analysis.make_lock("tracing.flight")
        self._worst = None
        self._count = 0

    def observe(self, tree):
        """Consider one finished span tree (dict with ``dur``)."""
        if tree is None or tree.get("dur") is None:
            return
        with self._lock:
            self._count += 1
            if self._worst is None or tree["dur"] > self._worst["dur"]:
                self._worst = tree

    def worst(self, reset=False):
        """The worst span tree since the last reset (None if none seen);
        ``reset=True`` also restarts the observation window (the
        Speedometer per-log-interval contract)."""
        with self._lock:
            out = self._worst
            if reset:
                self._worst = None
                self._count = 0
        return out

    @property
    def observed(self):
        return self._count

    def reset(self):
        self.worst(reset=True)


flight_recorder = FlightRecorder()

# the generation-plane analog of the slow-step recorder: the worst
# scheduler DECODE TICK's span tree since last read (`GenerationEngine`
# feeds it per tick; the HTTP /trace endpoint serves it as `worst_tick`
# beside `worst_step`, and watchdog diagnostic bundles capture it) —
# the "what did the slow tick actually do" black box for serving
tick_recorder = FlightRecorder()
