"""First-class SPMD model definitions (beyond the gluon model_zoo).

The gluon `model_zoo.vision` covers the reference's CNN zoo
(`python/mxnet/gluon/model_zoo/`); this package holds TPU-first model
families built directly on `mxnet_tpu.parallel` — sharded transformers with
ring attention, the long-context/distributed flagships the mesh design
exists for.
"""
from . import transformer
from .transformer import TransformerLMConfig, TransformerLM
from . import resnet
from .resnet import resnet50_symbol

__all__ = ["transformer", "TransformerLMConfig", "TransformerLM",
           "resnet", "resnet50_symbol"]
