"""Symbolic ResNet — the Module-API rendering of the headline workload.

Parity: the reference's `example/image-classification/symbols/resnet.py`
(residual_unit / resnet builders, the network its perf tables measure).
The gluon model_zoo covers the imperative spelling; this is the *symbolic*
one, so `Module.fit` — and with it the fused train-step path (one XLA
computation per step, `symbol/executor.py` `fused_step`) — can drive the
same ResNet-50 the benchmarks and the reference's 298.51 img/s baseline
use.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["resnet", "resnet50_symbol"]


def _residual_unit(data, num_filter, stride, dim_match, name,
                   bottle_neck=True, bn_mom=0.9):
    """One residual block (reference resnet.py `residual_unit`)."""
    if bottle_neck:
        bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(data=act1, num_filter=num_filter // 4,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, name=name + "_conv1")
        bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(data=act2, num_filter=num_filter // 4,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, name=name + "_conv2")
        bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn3")
        act3 = sym.Activation(data=bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(data=act3, num_filter=num_filter,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(data=act1, num_filter=num_filter,
                                       kernel=(1, 1), stride=stride,
                                       no_bias=True, name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name=name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(data=act1, num_filter=num_filter, kernel=(3, 3),
                            stride=stride, pad=(1, 1), no_bias=True,
                            name=name + "_conv1")
    bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name=name + "_bn2")
    act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(data=act2, num_filter=num_filter, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(data=act1, num_filter=num_filter,
                                   kernel=(1, 1), stride=stride, no_bias=True,
                                   name=name + "_sc")
    return conv2 + shortcut


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9):
    """Build a symbolic ResNet (reference resnet.py `resnet`)."""
    data = sym.Variable(name="data")
    data = sym.BatchNorm(data=data, fix_gamma=True, eps=2e-5,
                         momentum=bn_mom, name="bn_data")
    height = image_shape[1]
    if height <= 32:  # cifar-style stem
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, name="conv0")
    else:  # imagenet stem
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, name="conv0")
        body = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                             momentum=bn_mom, name="bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max")

    for i in range(num_stages):
        stride = (1, 1) if i == 0 else (2, 2)
        body = _residual_unit(body, filter_list[i + 1], stride, False,
                              name=f"stage{i + 1}_unit1",
                              bottle_neck=bottle_neck, bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = _residual_unit(body, filter_list[i + 1], (1, 1), True,
                                  name=f"stage{i + 1}_unit{j + 2}",
                                  bottle_neck=bottle_neck, bn_mom=bn_mom)
    bn1 = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name="bn1")
    relu1 = sym.Activation(data=bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7),
                        pool_type="avg", name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")


def resnet50_symbol(num_classes=1000, image_shape=(3, 224, 224)):
    """ResNet-50 v1 (the headline benchmark network) as a Symbol."""
    return resnet(units=[3, 4, 6, 3], num_stages=4,
                  filter_list=[64, 256, 512, 1024, 2048],
                  num_classes=num_classes, image_shape=image_shape,
                  bottle_neck=True)
