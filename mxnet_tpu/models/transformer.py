"""SPMD Transformer language model — the distributed/long-context flagship.

Mapping to the reference: its sequence-model story is the fused cuDNN RNN +
BucketingModule (`src/operator/rnn-inl.h`, `module/bucketing_module.py:36`;
SURVEY.md §5 "long-context: none"). The TPU-native replacement is a
transformer whose training step is ONE jitted SPMD program over a
dp×sp×tp(+fsdp) mesh:

* batch over 'dp', sequence over 'sp' (ring attention — exact attention
  with K/V circulating the ICI ring, `parallel/ring_attention.py`),
* Megatron-style tensor parallelism over 'tp' expressed as GSPMD sharding
  annotations (column-parallel in-proj, row-parallel out-proj — XLA inserts
  the psum),
* optional 'fsdp' parameter sharding.

Everything is bfloat16 on the MXU with fp32 master params and fp32 softmax.
"""
from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.collectives import sharding_constraint
from ..parallel.mesh import default_mesh
from ..parallel.ring_attention import ring_attention
from ..parallel.spmd import model_mesh


@dataclasses.dataclass(frozen=True)
class TransformerLMConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_layers: int = 4
    max_len: int = 2048
    dtype: str = "bfloat16"
    causal: bool = True
    tie_embeddings: bool = True
    # Mixture-of-Experts (beyond-parity; the GShard/Switch recipe):
    # moe_experts > 0 turns every `moe_every`-th FFN into a top-1-routed
    # expert layer whose expert dim shards over the 'ep' mesh axis (or the
    # 'dp' axis when no dedicated ep axis exists — the standard deployment:
    # all-to-all rides the data-parallel group).
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.5
    moe_aux_loss: float = 0.01


def _use_pallas_attention():
    """Fused flash kernel policy: ON by default on the TPU backend, OFF
    elsewhere (the interpret path is a debugging tool, not a CPU win);
    MXNET_PALLAS_ATTENTION=0/1 overrides either way."""
    import os

    flag = os.environ.get("MXNET_PALLAS_ATTENTION")
    if flag is not None:
        return flag == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _spec(mesh, *axes):
    return NamedSharding(mesh, P(*[a if (a in mesh.shape and mesh.shape[a] > 1) else None
                                   for a in axes]))


class TransformerLM:
    """Functional transformer LM bound to a mesh.

    params is a flat dict name -> jax.Array (sharded). All methods are
    pure; `init_params` places every weight with its partition spec.
    """

    def __init__(self, config, mesh=None):
        self.cfg = config
        # model_mesh: the MXNET_SPMD mesh when that gate is on (serving/
        # generation weights and the KV slab shard without plumbing),
        # else the ambient/default mesh — `default_mesh` semantics
        self.mesh = mesh or model_mesh()

    def _is_moe(self, i):
        c = self.cfg
        return c.moe_experts > 0 and (i % max(c.moe_every, 1)) == \
            max(c.moe_every, 1) - 1

    @property
    def _ep_axis(self):
        # dedicated 'ep' axis when the mesh has one, else experts shard
        # over the data-parallel group (GShard deployment)
        return "ep" if "ep" in self.mesh.shape else "dp"

    # -- parameters ---------------------------------------------------------

    def param_specs(self):
        c, mesh = self.cfg, self.mesh
        specs = {
            "embed": _spec(mesh, "tp", None),            # [V, D] vocab-sharded
            "pos_embed": _spec(mesh, None, None),        # [max_len, D]
            "ln_f_scale": _spec(mesh, None),
            "ln_f_bias": _spec(mesh, None),
        }
        ep = self._ep_axis
        for i in range(c.n_layers):
            specs.update({
                f"l{i}.ln1_scale": _spec(mesh, None),
                f"l{i}.ln1_bias": _spec(mesh, None),
                f"l{i}.wqkv": _spec(mesh, None, "tp"),   # [D, 3D] col-parallel
                f"l{i}.wo": _spec(mesh, "tp", None),     # [D, D] row-parallel
                f"l{i}.ln2_scale": _spec(mesh, None),
                f"l{i}.ln2_bias": _spec(mesh, None),
            })
            if self._is_moe(i):
                specs.update({
                    f"l{i}.router": _spec(mesh, None, None),       # [D, E]
                    f"l{i}.we1": _spec(mesh, ep, None, "tp"),      # [E, D, F]
                    f"l{i}.be1": _spec(mesh, ep, "tp"),            # [E, F]
                    f"l{i}.we2": _spec(mesh, ep, "tp", None),      # [E, F, D]
                    f"l{i}.be2": _spec(mesh, ep, None),            # [E, D]
                })
            else:
                specs.update({
                    f"l{i}.w1": _spec(mesh, None, "tp"),  # [D, F] col-parallel
                    f"l{i}.b1": _spec(mesh, "tp"),
                    f"l{i}.w2": _spec(mesh, "tp", None),  # [F, D] row-parallel
                    f"l{i}.b2": _spec(mesh, None),
                })
        if not c.tie_embeddings:
            specs["lm_head"] = _spec(mesh, None, "tp")
        return specs

    def init_params(self, key):
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        shapes = {
            "embed": (c.vocab_size, c.d_model),
            "pos_embed": (c.max_len, c.d_model),
            "ln_f_scale": (c.d_model,),
            "ln_f_bias": (c.d_model,),
        }
        for i in range(c.n_layers):
            shapes.update({
                f"l{i}.ln1_scale": (c.d_model,), f"l{i}.ln1_bias": (c.d_model,),
                f"l{i}.wqkv": (c.d_model, 3 * c.d_model),
                f"l{i}.wo": (c.d_model, c.d_model),
                f"l{i}.ln2_scale": (c.d_model,), f"l{i}.ln2_bias": (c.d_model,),
            })
            if self._is_moe(i):
                e = c.moe_experts
                shapes.update({
                    f"l{i}.router": (c.d_model, e),
                    f"l{i}.we1": (e, c.d_model, c.d_ff),
                    f"l{i}.be1": (e, c.d_ff),
                    f"l{i}.we2": (e, c.d_ff, c.d_model),
                    f"l{i}.be2": (e, c.d_model),
                })
            else:
                shapes.update({
                    f"l{i}.w1": (c.d_model, c.d_ff), f"l{i}.b1": (c.d_ff,),
                    f"l{i}.w2": (c.d_ff, c.d_model), f"l{i}.b2": (c.d_model,),
                })
        if not c.tie_embeddings:
            shapes["lm_head"] = (c.d_model, c.vocab_size)

        specs = self.param_specs()
        params = {}
        keys = jax.random.split(key, len(shapes))
        for (name, shape), k in zip(sorted(shapes.items()), keys):
            if name.endswith(("_scale",)):
                val = jnp.ones(shape, dt)
            elif name.endswith(("_bias", ".b1", ".b2", ".be1", ".be2")):
                val = jnp.zeros(shape, dt)
            else:
                # 3-D expert weights are per-expert matrices: fan over the
                # contracted dim, not the expert dim
                fan_in = shape[-2] if len(shape) == 3 else shape[0]
                val = (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(dt)
            params[name] = jax.device_put(val, specs[name])
        return params

    # -- forward ------------------------------------------------------------

    def _ln(self, x, scale, bias):
        x32 = x.astype(jnp.float32)
        mu = x32.mean(-1, keepdims=True)
        var = x32.var(-1, keepdims=True)
        out = (x32 - mu) * jax.lax.rsqrt(var + 1e-5)
        return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)

    def _attention(self, q, k, v):
        """Dispatch: ring attention if 'sp' is a real mesh axis, else local
        blockwise attention (same math, zero hops)."""
        mesh, c = self.mesh, self.cfg
        sp = mesh.shape.get("sp", 1)
        if sp > 1:
            from ..parallel.collectives import shard_map
            spec = P(("dp", "fsdp") if "fsdp" in mesh.shape else "dp", "sp", "tp", None)
            spec = P(*[a if (isinstance(a, tuple) or (a in mesh.shape and mesh.shape[a] > 1)) else None
                       for a in spec])

            def body(q, k, v):
                return ring_attention(q, k, v, "sp", sp, causal=c.causal)

            fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
            return fn(q, k, v)
        if _use_pallas_attention():
            # fused VMEM-resident flash kernel (ops/pallas_attention.py):
            # QK^T -> streaming softmax -> PV without the HBM round trip.
            # Falls through to the XLA blockwise path on shapes the kernel
            # does not tile.
            try:
                import os

                from ..ops.pallas_attention import flash_attention

                return flash_attention(
                    q, k, v, causal=c.causal,
                    interpret=os.environ.get(
                        "MXNET_PALLAS_INTERPRET") == "1")
            except (ValueError, RuntimeError):
                pass
        from ..parallel.ring_attention import _block_attn, _bhql_to_bqhl, _full_causal_bias
        bias = _full_causal_bias(q.shape[1], k.shape[1]) if c.causal else None
        o, m, l = _block_attn(q, k, v, bias)
        return o / _bhql_to_bqhl(l)

    def _moe_ffn(self, i, params, x):
        """Top-1 ("Switch") expert FFN — the GShard GROUPED dispatch/
        combine einsum recipe with STATIC per-group capacity: tokens are
        grouped by batch row (G=B), each group routes at most C =
        ceil(cf·L/E) tokens to an expert, dispatch (G, L, E, C) one-hots
        move kept tokens into expert buffers (the all-to-all when experts
        shard over ep/dp), experts batch-apply their FFN, combine scales
        by the router gate. Grouping keeps dispatch memory O(S·E·C) with
        C ∝ L/E instead of the ungrouped O(S²). Returns (out, aux)."""
        c = self.cfg
        dt = x.dtype
        B, L, D = x.shape
        E = c.moe_experts
        C = max(1, int(np.ceil(c.moe_capacity_factor * L / E)))

        logits = (x.astype(jnp.float32) @
                  params[f"l{i}.router"].astype(jnp.float32))     # (B, L, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                       # (B, L)
        gate = jnp.max(probs, axis=-1)                            # (B, L)

        mask = jax.nn.one_hot(expert, E, dtype=jnp.float32)       # (B, L, E)
        # position of each token within its expert's PER-GROUP buffer
        pos = (jnp.cumsum(mask, axis=1) - 1.0) * mask             # (B, L, E)
        keep = mask * (pos < C)
        # load-balancing aux loss (Switch eq. 4) from the PRE-capacity
        # assignment — post-capacity f saturates at cf/E exactly when
        # routing collapses, killing the balance gradient
        f = mask.mean(axis=(0, 1))
        pmean = probs.mean(axis=(0, 1))
        aux = E * jnp.sum(f * pmean)

        slot = jax.nn.one_hot(jnp.sum(pos * keep, axis=2).astype(jnp.int32),
                              C, dtype=jnp.float32)               # (B, L, C)
        dispatch = keep[:, :, :, None] * slot[:, :, None, :]      # (B, L, E, C)
        combine = dispatch * gate[:, :, None, None]

        xe = jnp.einsum("glec,gld->gecd", dispatch.astype(dt), x)  # (B,E,C,D)
        h1 = jax.nn.gelu(
            jnp.einsum("gecd,edf->gecf", xe, params[f"l{i}.we1"]) +
            params[f"l{i}.be1"].astype(dt)[None, :, None, :])
        h2 = jnp.einsum("gecf,efd->gecd", h1, params[f"l{i}.we2"]) + \
            params[f"l{i}.be2"].astype(dt)[None, :, None, :]
        out = jnp.einsum("glec,gecd->gld", combine.astype(dt), h2)
        return out, aux

    def forward(self, params, tokens, return_aux=False):
        """tokens [B, L] int32 → logits [B, L, V] (compute dtype, fp32 at
        loss); with return_aux also the summed MoE load-balance loss."""
        c, mesh = self.cfg, self.mesh
        dt = jnp.dtype(c.dtype)
        B, L = tokens.shape
        act = P(*[a if (a in mesh.shape and mesh.shape[a] > 1) else None
                  for a in ("dp", "sp", None)])

        h = jnp.take(params["embed"], tokens, axis=0).astype(dt)
        h = h + params["pos_embed"][None, :L].astype(dt)
        h = lax.with_sharding_constraint(h, NamedSharding(mesh, act))
        aux_total = jnp.asarray(0.0, jnp.float32)

        for i in range(c.n_layers):
            ln1 = self._ln(h, params[f"l{i}.ln1_scale"], params[f"l{i}.ln1_bias"])
            qkv = ln1 @ params[f"l{i}.wqkv"]              # [B,L,3D] heads on tp
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hd = c.d_model // c.n_heads
            q = q.reshape(B, L, c.n_heads, hd)
            k = k.reshape(B, L, c.n_heads, hd)
            v = v.reshape(B, L, c.n_heads, hd)
            attn = self._attention(q, k, v).reshape(B, L, c.d_model)
            h = h + attn @ params[f"l{i}.wo"]              # row-parallel: XLA psums over tp
            h = lax.with_sharding_constraint(h, NamedSharding(mesh, act))
            ln2 = self._ln(h, params[f"l{i}.ln2_scale"], params[f"l{i}.ln2_bias"])
            if self._is_moe(i):
                ff, aux = self._moe_ffn(i, params, ln2)
                aux_total = aux_total + aux
                h = h + ff
            else:
                ff = jax.nn.gelu(ln2 @ params[f"l{i}.w1"] + params[f"l{i}.b1"].astype(dt))
                h = h + ff @ params[f"l{i}.w2"] + params[f"l{i}.b2"].astype(dt)
            h = lax.with_sharding_constraint(h, NamedSharding(mesh, act))

        h = self._ln(h, params["ln_f_scale"], params["ln_f_bias"])
        head = params["embed"].T if c.tie_embeddings else params["lm_head"]
        logits = h @ head.astype(dt)
        if return_aux:
            return logits, aux_total
        return logits

    # -- incremental decoding (serving/generation) ---------------------------
    #
    # The O(1)-per-token cache discipline of arXiv:2603.09555: one
    # preallocated KV slab of FIXED shape holds every live session's keys
    # and values, `prefill` fills a slot's rows [0, L) from the prompt in
    # one full-length pass, and `decode_step` extends every live slot by
    # exactly one token — a dynamic_update_slice write plus attention over
    # the (masked) slab row, never a recompile, never O(T) recomputation.
    # Both are pure functions of (params, cache, ...) so the serving engine
    # can jit them once per shape with the cache buffers donated.

    def _slab_sharding(self):
        """The KV slab's layout: heads axis over 'tp' when the mesh has a
        real tp axis that divides n_heads (the serving twin of the SPMD
        weight sharding — per-head attention is independent, so the slab
        shards cleanly on heads and decode K/V writes stay local), else
        replicated. Every slab allocation AND every cache-returning
        method pins this layout, so the donated decode/prefill buffers
        alias across ticks."""
        c = self.cfg
        tp = self.mesh.shape.get("tp", 1)
        if tp > 1 and c.n_heads % tp == 0:
            return NamedSharding(self.mesh, P(None, None, "tp", None, None))
        return NamedSharding(self.mesh, P())

    def init_cache(self, max_slots, max_len=None):
        """Allocate the slot-based KV slab: two arrays (keys, values) of
        shape ``[max_slots, n_layers, n_heads, max_len, head_dim]`` in the
        compute dtype, zeroed, laid out per :meth:`_slab_sharding` on the
        model's mesh (heads over 'tp' when present — the slab stops being
        replicated under `MXNET_SPMD=tp=K`). Slot contents are garbage
        until a `prefill` claims the slot; reads are always masked by the
        slot's current length, so stale rows from a previous occupant are
        never attended."""
        c = self.cfg
        max_len = c.max_len if max_len is None else int(max_len)
        if max_len > c.max_len:
            raise ValueError(f"cache max_len {max_len} exceeds the model's "
                             f"positional range {c.max_len}")
        hd = c.d_model // c.n_heads
        shape = (int(max_slots), c.n_layers, c.n_heads, max_len, hd)
        sh = self._slab_sharding()
        dt = jnp.dtype(c.dtype)
        return (jax.device_put(jnp.zeros(shape, dt), sh),
                jax.device_put(jnp.zeros(shape, dt), sh))

    def _head(self, params):
        return (params["embed"].T if self.cfg.tie_embeddings
                else params["lm_head"])

    def prefill(self, params, cache_k, cache_v, tokens, length, slot):
        """Full-prompt forward for ONE session, writing its K/V into slot
        ``slot`` rows ``[0, Lb)`` of the slab and returning the logits at
        the last REAL token (position ``length - 1``) — the distribution
        the first generated token is sampled from.

        tokens : int32 [Lb]   prompt padded (with anything) up to the
                              compile bucket; padded positions produce
                              garbage K/V that the length mask keeps
                              unread forever.
        length : int32 scalar real prompt length (1 <= length <= Lb)
        slot   : int32 scalar slab row to fill (traced — one executable
                              serves every slot)

        Returns ``(logits [V] fp32, cache_k, cache_v)``. Pure; jit with
        the two cache operands donated.
        """
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        Lb = tokens.shape[0]
        hd = c.d_model // c.n_heads
        scale = 1.0 / np.sqrt(hd)
        h = jnp.take(params["embed"], tokens, axis=0).astype(dt)     # [Lb,D]
        h = h + params["pos_embed"][:Lb].astype(dt)
        # additive causal mask, large-negative (not -inf: a fully-masked
        # row must softmax to harmless garbage, not NaN)
        ar = jnp.arange(Lb)
        causal = jnp.where(ar[:, None] >= ar[None, :], 0.0, -1e9)   # [Lb,Lb]
        for i in range(c.n_layers):
            ln1 = self._ln(h, params[f"l{i}.ln1_scale"],
                           params[f"l{i}.ln1_bias"])
            qkv = ln1 @ params[f"l{i}.wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(Lb, c.n_heads, hd)
            k = k.reshape(Lb, c.n_heads, hd)
            v = v.reshape(Lb, c.n_heads, hd)
            # slab write: [1, 1, H, Lb, hd] block at (slot, layer, 0, 0, 0)
            cache_k = lax.dynamic_update_slice(
                cache_k, k.transpose(1, 0, 2)[None, None].astype(cache_k.dtype),
                (slot, i, 0, 0, 0))
            cache_v = lax.dynamic_update_slice(
                cache_v, v.transpose(1, 0, 2)[None, None].astype(cache_v.dtype),
                (slot, i, 0, 0, 0))
            s = jnp.einsum("qhd,khd->hqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s + causal[None], axis=-1).astype(dt)
            attn = jnp.einsum("hqk,khd->qhd", p, v).reshape(Lb, c.d_model)
            h = h + attn @ params[f"l{i}.wo"]
            ln2 = self._ln(h, params[f"l{i}.ln2_scale"],
                           params[f"l{i}.ln2_bias"])
            if self._is_moe(i):
                # batch-1 grouped dispatch; note: capacity is computed at
                # the BUCKET length, so under heavy routing imbalance a
                # bucket-padded prefill can keep tokens a shorter forward
                # would have dropped (decode_step always keeps: C=1, L=1)
                ff, _ = self._moe_ffn(i, params, ln2[None])
                h = h + ff[0]
            else:
                ff = jax.nn.gelu(ln2 @ params[f"l{i}.w1"]
                                 + params[f"l{i}.b1"].astype(dt))
                h = h + ff @ params[f"l{i}.w2"] + params[f"l{i}.b2"].astype(dt)
        h = self._ln(h, params["ln_f_scale"], params["ln_f_bias"])
        last = lax.dynamic_slice_in_dim(h, length - 1, 1, axis=0)    # [1,D]
        logits = (last @ self._head(params).astype(dt)).astype(jnp.float32)
        sh = self._slab_sharding()
        return (logits[0], sharding_constraint(cache_k, sh),
                sharding_constraint(cache_v, sh))

    def prefill_at(self, params, cache_k, cache_v, tokens, length, slot,
                   offset):
        """Suffix prefill: the prompt's UNMATCHED tail after a prefix-cache
        fork. The slot's rows ``[0, offset)`` already hold the K/V of the
        prompt's first ``offset`` tokens (copied slot-to-slot from a cached
        entry by the fork executable); this forward consumes only the
        remaining ``length`` tokens, writes their K/V into rows
        ``[offset, offset + Lb)`` and returns the logits at the last REAL
        suffix token — so a cache hit pays O(suffix), not O(prompt).

        tokens : int32 [Lb]   suffix padded up to the compile bucket
        length : int32 scalar real suffix length (1 <= length <= Lb)
        slot   : int32 scalar slab row (traced)
        offset : int32 scalar matched-prefix length (traced — ONE
                              executable per bucket serves every split
                              point, the compile-once discipline)

        Unlike :meth:`prefill` (whose attention is the Lb x Lb causal
        block), the suffix block must also attend the cached rows, so each
        layer scores the suffix queries against the slot's FULL slab row
        masked to ``j <= offset + i`` — the decode-step mask family, at
        Lb x slab_len cost. Returns ``(logits [V] fp32, cache_k,
        cache_v)``. Pure; jit with the cache operands donated.
        """
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        Lb = tokens.shape[0]
        L = cache_k.shape[3]
        hd = c.d_model // c.n_heads
        scale = 1.0 / np.sqrt(hd)
        pos = offset + jnp.arange(Lb)
        h = jnp.take(params["embed"], tokens, axis=0).astype(dt)     # [Lb,D]
        # jnp.take clips out-of-range positions (pad rows past the model's
        # positional range read row max_len-1 — garbage the mask hides)
        h = h + jnp.take(params["pos_embed"], pos, axis=0).astype(dt)
        # suffix token i attends slab rows j <= offset + i: the cached
        # prefix plus causal-within-suffix, one mask over the whole row.
        # Large-negative, not -inf (finite garbage for fully-masked rows)
        mask = jnp.where(jnp.arange(L)[None, None, :]
                         <= pos[None, :, None], 0.0, -1e9)     # [1,Lb,L]
        for i in range(c.n_layers):
            ln1 = self._ln(h, params[f"l{i}.ln1_scale"],
                           params[f"l{i}.ln1_bias"])
            qkv = ln1 @ params[f"l{i}.wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(Lb, c.n_heads, hd)
            k = k.reshape(Lb, c.n_heads, hd)
            v = v.reshape(Lb, c.n_heads, hd)
            # slab write: [1, 1, H, Lb, hd] block at (slot, layer, 0,
            # offset, 0) — rows [0, offset) stay the forked prefix
            cache_k = lax.dynamic_update_slice(
                cache_k, k.transpose(1, 0, 2)[None, None].astype(cache_k.dtype),
                (slot, i, 0, offset, 0))
            cache_v = lax.dynamic_update_slice(
                cache_v, v.transpose(1, 0, 2)[None, None].astype(cache_v.dtype),
                (slot, i, 0, offset, 0))
            ck_i = lax.dynamic_slice(
                cache_k, (slot, i, 0, 0, 0),
                (1, 1, c.n_heads, L, hd))[0, 0]                # [H,L,hd]
            cv_i = lax.dynamic_slice(
                cache_v, (slot, i, 0, 0, 0),
                (1, 1, c.n_heads, L, hd))[0, 0]
            s = jnp.einsum("qhd,hkd->hqk", q, ck_i.astype(dt),
                           preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s + mask, axis=-1).astype(dt)
            attn = jnp.einsum("hqk,hkd->qhd", p,
                              cv_i.astype(dt)).reshape(Lb, c.d_model)
            h = h + attn @ params[f"l{i}.wo"]
            ln2 = self._ln(h, params[f"l{i}.ln2_scale"],
                           params[f"l{i}.ln2_bias"])
            if self._is_moe(i):
                # batch-1 grouped dispatch, as in prefill
                ff, _ = self._moe_ffn(i, params, ln2[None])
                h = h + ff[0]
            else:
                ff = jax.nn.gelu(ln2 @ params[f"l{i}.w1"]
                                 + params[f"l{i}.b1"].astype(dt))
                h = h + ff @ params[f"l{i}.w2"] + params[f"l{i}.b2"].astype(dt)
        h = self._ln(h, params["ln_f_scale"], params["ln_f_bias"])
        last = lax.dynamic_slice_in_dim(h, length - 1, 1, axis=0)    # [1,D]
        logits = (last @ self._head(params).astype(dt)).astype(jnp.float32)
        sh = self._slab_sharding()
        return (logits[0], sharding_constraint(cache_k, sh),
                sharding_constraint(cache_v, sh))

    def decode_step(self, params, cache_k, cache_v, tokens, positions):
        """One fused incremental step over the WHOLE slot slab: each slot
        consumes one token, writes its K/V at ``positions[s]`` and attends
        over rows ``[0, positions[s]]`` — O(1) work per token in generated
        length, every slot in one XLA program.

        tokens    : int32 [S] the token extending each slot (dead slots:
                    anything — their output is discarded by the engine)
        positions : int32 [S] the index each token occupies (== the slot's
                    current length; dead slots: 0 — their garbage write
                    lands in a row the length mask hides from any future
                    occupant, because a new session's prefill rewrites
                    [0, Lb) first)

        Returns ``(logits [S, V] fp32, cache_k, cache_v)``. Pure; jit with
        the cache operands donated. One executable serves every admission/
        eviction pattern — continuous batching never recompiles.
        """
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        S = tokens.shape[0]
        L = cache_k.shape[3]
        hd = c.d_model // c.n_heads
        scale = 1.0 / np.sqrt(hd)
        h = jnp.take(params["embed"], tokens, axis=0).astype(dt)      # [S,D]
        h = h + jnp.take(params["pos_embed"], positions, axis=0).astype(dt)
        # per-slot length mask over the slab row: attend j <= positions[s]
        # (<=: the token just written attends to itself). Large-negative,
        # not -inf — a dead slot masks everything and must produce finite
        # garbage, not NaN.
        mask = jnp.where(jnp.arange(L)[None, None, :]
                         <= positions[:, None, None], 0.0, -1e9)    # [S,1,L]
        for i in range(c.n_layers):
            ln1 = self._ln(h, params[f"l{i}.ln1_scale"],
                           params[f"l{i}.ln1_bias"])
            qkv = ln1 @ params[f"l{i}.wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(S, c.n_heads, hd)
            k = k.reshape(S, c.n_heads, hd)
            v = v.reshape(S, c.n_heads, hd)

            def write(slab, new):
                # per-slot dynamic_update_slice at that slot's position:
                # [H, 1, hd] block into the slot's [H, L, hd] layer page
                return jax.vmap(lambda page, u, p: lax.dynamic_update_slice(
                    page, u, (0, p, 0)))(
                        slab[:, i], new[:, :, None, :].astype(slab.dtype),
                        positions)

            ck_i = write(cache_k, k)                           # [S,H,L,hd]
            cv_i = write(cache_v, v)
            cache_k = cache_k.at[:, i].set(ck_i)
            cache_v = cache_v.at[:, i].set(cv_i)
            s = jnp.einsum("shd,shld->shl", q, ck_i.astype(dt),
                           preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s + mask, axis=-1).astype(dt)
            attn = jnp.einsum("shl,shld->shd", p,
                              cv_i.astype(dt)).reshape(S, c.d_model)
            h = h + attn @ params[f"l{i}.wo"]
            ln2 = self._ln(h, params[f"l{i}.ln2_scale"],
                           params[f"l{i}.ln2_bias"])
            if self._is_moe(i):
                # [S, 1, D]: every slot is its own routing group of one
                # token with capacity 1, so a decoded token is ALWAYS
                # routed (never capacity-dropped, unlike training forward)
                ff, _ = self._moe_ffn(i, params, ln2[:, None, :])
                h = h + ff[:, 0]
            else:
                ff = jax.nn.gelu(ln2 @ params[f"l{i}.w1"]
                                 + params[f"l{i}.b1"].astype(dt))
                h = h + ff @ params[f"l{i}.w2"] + params[f"l{i}.b2"].astype(dt)
        h = self._ln(h, params["ln_f_scale"], params["ln_f_bias"])
        logits = (h @ self._head(params).astype(dt)).astype(jnp.float32)
        sh = self._slab_sharding()
        return (logits, sharding_constraint(cache_k, sh),
                sharding_constraint(cache_v, sh))

    def verify_step(self, params, cache_k, cache_v, tokens, positions):
        """Speculative-decoding verify: advance every slot by ``K = k + 1``
        tokens in ONE executable. ``tokens[:, 0]`` is each slot's last
        committed token, ``tokens[:, 1:]`` the draft's k proposals; the
        returned logits row ``i`` is the model's next-token distribution
        after consuming ``tokens[:, :i+1]`` — the engine accepts the
        longest draft prefix whose proposals match the greedy argmaxes and
        rolls the rest back by NOT advancing ``positions`` past it (the
        rejected rows beyond the new frontier are never attended and are
        overwritten sequentially before they could be).

        tokens    : int32 [S, K]  fed block per slot (dead slots: anything)
        positions : int32 [S]     row the block starts at (== slot length)

        Returns ``(logits [S, K, V] fp32, cache_k, cache_v)``.

        Structure is deliberately K *unrolled* :meth:`decode_step` graphs
        chained through the slab — NOT a batched K-query attention block.
        The per-token math is then structurally identical to the
        non-speculative decode executable, which is what makes speculative
        greedy output BIT-EXACT with the plain path (a batched
        formulation reassociates the attention reductions and can flip an
        argmax by a ulp — the PR 6/8 FMA precedent). On accelerators the
        unrolled chain still amortizes K dispatches and K HBM round-trips
        of host scheduling into one program launch, which is where the
        speculative win lives at decode batch sizes. Pure; jit with the
        cache operands donated.
        """
        steps = []
        for i in range(tokens.shape[1]):
            logits, cache_k, cache_v = self.decode_step(
                params, cache_k, cache_v, tokens[:, i], positions + i)
            steps.append(logits)
        return jnp.stack(steps, axis=1), cache_k, cache_v

    # -- training -----------------------------------------------------------

    def loss(self, params, tokens, targets):
        logits, aux = self.forward(params, tokens, return_aux=True)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return nll.mean() + self.cfg.moe_aux_loss * aux

    def make_train_step(self, optimizer=None, lr=1e-3):
        """Return jitted (params, opt_state, tokens, targets) -> (params,
        opt_state, loss): Adam in fp32 master precision."""
        mesh = self.mesh
        b1, b2, eps = 0.9, 0.999, 1e-8

        def init_opt(params):
            return {k: (jnp.zeros(v.shape, jnp.float32),
                        jnp.zeros(v.shape, jnp.float32)) for k, v in params.items()}

        def step(params, opt_state, tokens, targets, step_no):
            loss, grads = jax.value_and_grad(self.loss)(params, tokens, targets)
            new_p, new_s = {}, {}
            t = step_no.astype(jnp.float32) + 1
            for name, p in params.items():
                g = grads[name].astype(jnp.float32)
                m, v = opt_state[name]
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * g * g
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
                new_p[name] = (p.astype(jnp.float32) -
                               lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
                new_s[name] = (m, v)
            return new_p, new_s, loss

        specs = self.param_specs()
        state_specs = {k: (s, s) for k, s in specs.items()}
        data_spec = NamedSharding(mesh, P(*[a if (a in mesh.shape and mesh.shape[a] > 1) else None
                                            for a in ("dp", "sp")]))
        repl = NamedSharding(mesh, P())
        fn = jax.jit(step,
                     in_shardings=(specs, state_specs, data_spec, data_spec, repl),
                     out_shardings=(specs, state_specs, repl))
        return fn, init_opt

    def shard_tokens(self, tokens):
        mesh = self.mesh
        spec = NamedSharding(mesh, P(*[a if (a in mesh.shape and mesh.shape[a] > 1) else None
                                       for a in ("dp", "sp")]))
        return jax.device_put(jnp.asarray(tokens, jnp.int32), spec)
