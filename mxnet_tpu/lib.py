"""Loader for the native C++ runtime (librt_tpu.so).

The reference loads libmxnet.so via ctypes (`python/mxnet/base.py`); here the
native library provides the host-side runtime only (dependency engine for
IO/checkpoint ordering, RecordIO reader, shared-memory arena) — compute is
XLA. Everything degrades gracefully to pure-python fallbacks when the .so
has not been built (`make -C src`).
"""
from __future__ import annotations

import ctypes
import os
import threading

_lib = None
_lib_tried = False
_engine = None
_lock = threading.Lock()

_LIB_NAMES = ("librt_tpu.so",)


def _find_lib():
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "_native"),
        os.path.join(os.path.dirname(here), "build"),
        os.path.join(os.path.dirname(here), "src"),
    ]
    for d in candidates:
        for n in _LIB_NAMES:
            p = os.path.join(d, n)
            if os.path.exists(p):
                return p
    return None


def get_lib():
    global _lib, _lib_tried
    with _lock:
        if not _lib_tried:
            _lib_tried = True
            path = _find_lib()
            if path:
                try:
                    _lib = ctypes.CDLL(path)
                except OSError:
                    _lib = None
    return _lib


def native_available():
    return get_lib() is not None


def native_engine():
    """Python-facing handle to the native host engine; None if not built."""
    global _engine
    lib = get_lib()
    if lib is None:
        return None
    with _lock:
        if _engine is None:
            from .native_engine import NativeEngine

            _engine = NativeEngine(lib)
    return _engine
