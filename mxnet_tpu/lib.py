"""Loader for the native C++ runtime (librt_tpu.so).

The reference loads libmxnet.so via ctypes (`python/mxnet/base.py`); here the
native library provides the host-side runtime only (dependency engine for
IO/checkpoint ordering, RecordIO reader, shared-memory arena) — compute is
XLA. Everything degrades gracefully to pure-python fallbacks when the .so
has not been built (`make -C src`).
"""
from __future__ import annotations

import ctypes
import os
import threading

_lib = None
_lib_tried = False
_engine = None
_lock = threading.Lock()

_LIB_NAMES = ("librt_tpu.so",)


def _find_lib():
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "_native"),
        os.path.join(os.path.dirname(here), "build"),
        os.path.join(os.path.dirname(here), "src"),
    ]
    for d in candidates:
        for n in _LIB_NAMES:
            p = os.path.join(d, n)
            if os.path.exists(p):
                return p
    return None


def _try_build():
    """Build librt_tpu.so from src/ if a toolchain is present (`make -C src`).
    Failures are silent (everything has a pure-python fallback) and cached
    via a marker file so forked workers / later processes don't each re-run
    a doomed compile."""
    import shutil
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(os.path.dirname(here), "src")
    if not os.path.isdir(src) or shutil.which("make") is None:
        return
    marker = os.path.join(here, "_native", ".build_failed")
    if os.path.exists(marker):
        return
    try:
        subprocess.run(["make", "-C", src], capture_output=True, timeout=120)
    except Exception:
        pass
    if _find_lib() is None:
        try:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as f:
                f.write("native build failed; delete this file to retry\n")
        except OSError:
            pass


def get_lib():
    global _lib, _lib_tried
    with _lock:
        if not _lib_tried:
            _lib_tried = True
            path = _find_lib()
            if path is None and os.environ.get("MXNET_BUILD_NATIVE", "1") == "1":
                _try_build()
                path = _find_lib()
            if path:
                try:
                    _lib = ctypes.CDLL(path)
                except OSError:
                    _lib = None
    return _lib


def native_available():
    return get_lib() is not None


def native_engine():
    """Python-facing handle to the native host engine; None if not built."""
    global _engine
    lib = get_lib()
    if lib is None:
        return None
    with _lock:
        if _engine is None:
            from .native_engine import NativeEngine

            nthreads = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4"))
            _engine = NativeEngine(lib, num_threads=nthreads)
    return _engine


def native_recordio(path):
    """Native mmap RecordIO index for `path`; None if the .so isn't built."""
    lib = get_lib()
    if lib is None:
        return None
    from .native_engine import NativeRecordIO

    return NativeRecordIO(lib, path)


def shared_memory(name, size=None, create=False):
    """Named POSIX shm segment (CPUSharedStorageManager role); None if the
    .so isn't built."""
    lib = get_lib()
    if lib is None:
        return None
    from .native_engine import SharedMemoryArena

    return SharedMemoryArena(lib, name, size=size, create=create)


_imgpipe = None


def native_imgpipe(num_threads=4):
    """Native JPEG decode+augment pipe; None when the .so (or its libjpeg
    support) is absent."""
    global _imgpipe
    lib = get_lib()
    if lib is None:
        return None
    with _lock:
        if _imgpipe is None:
            from .native_engine import NativeImagePipe

            try:
                _imgpipe = NativeImagePipe(lib, num_threads=num_threads)
            except OSError:
                _imgpipe = False
    return _imgpipe or None


def shm_unlink(name):
    """Unlink a named shm segment without attaching (cleanup of segments
    whose content will never be read — abandoned DataLoader batches)."""
    lib = get_lib()
    if lib is None:
        return
    from .native_engine import _bind

    _bind(lib).rt_shm_unlink(name.encode())
