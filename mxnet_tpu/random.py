"""Random state management.

Parity: `python/mxnet/random.py` (seed) + the reference's per-context
`ResourceRequest::kRandom` PRNG resources (`src/resource.cc:174-197`).

TPU-native design: the underlying PRNG is jax's stateless threefry. A
**key provider** hides the functional key threading behind MXNet's stateful
API:

- ``EagerKeyProvider`` — process-global state; every sampler call splits a
  fresh subkey (used in eager mode).
- ``TraceKeyProvider`` — used while capturing a graph (CachedOp / Symbol
  executor): the base key is a *traced argument* of the compiled program and
  samplers derive subkeys with ``fold_in(base, counter)``, so each executable
  invocation gets fresh randomness with zero recompilation.

Bit-exactness with the reference's MT19937/Philox streams is explicitly not a
goal (documented divergence, SURVEY.md §7 "RNG parity").
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_provider", "TraceKeyProvider"]

_state = threading.local()


class EagerKeyProvider:
    def __init__(self, seed_=0):
        self._key = jax.random.PRNGKey(seed_)
        self._lock = threading.Lock()

    def next_key(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def reseed(self, seed_):
        with self._lock:
            self._key = jax.random.PRNGKey(seed_)


class TraceKeyProvider:
    """Derives per-op subkeys from a (possibly traced) base key."""

    def __init__(self, base_key):
        self.base = base_key
        self.counter = 0

    def next_key(self):
        k = jax.random.fold_in(self.base, self.counter)
        self.counter += 1
        return k

    def __enter__(self):
        push_provider(self)
        return self

    def __exit__(self, *a):
        pop_provider()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [EagerKeyProvider(0)]
    return _state.stack


def push_provider(p):
    _stack().append(p)


def pop_provider():
    _stack().pop()


def current_provider():
    return _stack()[-1]


def next_key():
    return current_provider().next_key()


def seed(seed_state, ctx="all"):
    """Seed the global RNG (parity: `python/mxnet/random.py:35`).
    ``ctx`` is accepted for API compatibility; TPU PRNG state is host-side.

    Also reseeds the LIBRARY-OWNED initializer RNG
    (`mxnet_tpu/initializer.py` _INIT_RNG) so `mx.random.seed(n)` makes
    parameter initialization reproducible (the reference contract — it
    seeds the per-context mxnet RNGs its C++ initializers use) without
    clobbering the user's global numpy stream."""
    from . import initializer as _init

    root = _stack()[0]
    if isinstance(root, EagerKeyProvider):
        root.reseed(int(seed_state))
    _init._INIT_RNG.seed(int(seed_state) % (2 ** 32))


# nd.random / sym.random namespaces are populated by ndarray/symbol register.


def derive_host_seed():
    """A 32-bit seed for HOST-side randomized ops (graph samplers, shuffle
    fallbacks): drawn from the active key provider so `mx.random.seed`
    controls host RNG reproducibly too."""
    import numpy as _np

    k = next_key()
    data = jax.random.key_data(k) if hasattr(jax.random, "key_data") else k
    return int(_np.asarray(data).ravel()[-1]) & 0x7FFFFFFF
