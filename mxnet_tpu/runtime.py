"""Runtime feature detection.

Parity: `python/mxnet/runtime.py` + `src/libinfo.cc` (`mx.runtime.Features`).
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    import jax

    feats = {
        "TPU": any(d.platform != "cpu" for d in jax.devices()),
        "CPU": True,
        "XLA": True,
        "PALLAS": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": False,
        "DIST_KVSTORE": True,
        "CUDA": False,
        "CUDNN": False,
        "MKLDNN": False,
        "OPENCV": _has("cv2"),
        "SIGNAL_HANDLER": True,
        "NATIVE_ENGINE": _native(),
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _has(mod):
    import importlib.util

    return importlib.util.find_spec(mod) is not None


def _native():
    try:
        from . import lib

        return lib.native_available()
    except Exception:
        return False


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
