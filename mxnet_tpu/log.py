"""Logging utilities (parity: `python/mxnet/log.py` — get_logger with
level-colored console output or plain file output)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger"]

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

_LEVEL_CHAR = {logging.CRITICAL: "C", logging.ERROR: "E",
               logging.WARNING: "W", logging.INFO: "I",
               logging.DEBUG: "D"}


class _Formatter(logging.Formatter):
    """Single-letter level prefix, colorized on a tty (reference log.py
    _Formatter)."""

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        char = _LEVEL_CHAR.get(record.levelno, "U")
        if self.colored and record.levelno in (logging.ERROR,
                                               logging.CRITICAL):
            prefix = f"\x1b[31m{char}\x1b[0m"
        elif self.colored and record.levelno == logging.WARNING:
            prefix = f"\x1b[33m{char}\x1b[0m"
        else:
            prefix = char
        self._style._fmt = (prefix + "%(asctime)s %(process)d "
                            "%(pathname)s:%(lineno)d] %(message)s")
        return super().format(record)


def _defer_to_root(record):
    """Handler filter: once the user configures the root logger
    (`logging.basicConfig`, pytest's capture, a FileHandler), records reach
    it via propagation — our default stream handler must then go quiet or
    every line prints twice. One configurable stream, with out-of-the-box
    visibility when nothing is configured."""
    return not logging.getLogger().handlers


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Get a customized logger (reference log.py:56): file handler when
    `filename` is given, else a stream handler with colored levels."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
            hdlr.setFormatter(_Formatter(colored=False))
        else:
            hdlr = logging.StreamHandler(sys.stderr)
            hdlr.setFormatter(_Formatter(
                colored=getattr(sys.stderr, "isatty", lambda: False)()))
            hdlr.addFilter(_defer_to_root)
        logger.addHandler(hdlr)
        # level set ONLY at first init (reference log.py) — later
        # get_logger calls must not clobber a configured level
        logger.setLevel(level)
    return logger
