"""The performance observatory: roofline attribution against MEASURED peaks.

Every headline number the repo produced before this module (img/s,
tokens/s, "4% MFU") was unanchored wall clock — nothing related measured
time to what the hardware *could* do, so a 4%-MFU bug and a 4%-MFU
ceiling read identically (the ROADMAP's falsifiability gap). This module
closes the loop in three moves:

1. **Measured-peak probes** (:func:`peaks`) — tiny microbenchmarks per
   device kind: sustained matmul FLOP/s per dtype, HBM/memcpy bandwidth,
   and collective (all-reduce) bandwidth over the visible devices. Peaks
   are measured once and persisted under ``MXNET_OBSERVATORY_DIR`` with
   provenance (backend, device kind, device count, probe sizes); a
   provenance mismatch re-probes. Probe executables compile under the
   named ``CompileCache("observatory")`` so their compiles stay counted.

2. **Per-executable attribution** (:func:`attribution` / :func:`summary`)
   — from the cost analysis CompileCache records per entry (FLOPs, bytes
   accessed — one AOT pass shared with ``entry_memory``) plus the
   compiled program's collective inventory (``analysis.parse_collectives``),
   compute each observed lane's roofline bound (compute- vs bandwidth- vs
   comm-bound), predicted floor time, and achieved MFU/MBU from the
   measured steady-state time. Surfaced as telemetry gauges (``step.mfu``,
   ``step.mbu``, ``generation.tick_mbu``, ``*.comm_fraction``,
   ``step.host_gap_us`` = wall − device-busy), the ``/roofline`` HTTP
   endpoint next to ``/metrics``, and worst-offender rows in
   ``tools/telemetry_report.py``.

3. **Lane observations** (:func:`observe`) — the instrumented hot paths
   (``Executor.fused_step``, ``Predictor._run``, the generation
   scheduler's ``_tick``) report which executable ran and how long it
   took; the off cost is exactly ONE module-attribute read per site
   (``observatory._enabled``), pinned by a fresh-subprocess test like the
   telemetry/health/tracing planes.

Attribution math (the classic roofline):

* ``t_compute = flops / peak_flops(dtype)``
* ``t_memory  = bytes_accessed / peak_hbm_bytes_per_s``
* ``t_comm    = collective_bytes / peak_collective_bytes_per_s``
* ``predicted_floor_s = max(of the three)`` — its argmax is the bound
* ``mfu = (flops / measured_s) / peak_flops`` and
  ``mbu = (bytes_accessed / measured_s) / peak_hbm`` — achieved
  utilization against MEASURED (probe-derived, never spec-sheet) peaks.

On CPU the measured "HBM" bandwidth is host memory bandwidth and the
matmul peak is whatever the BLAS path sustains — the *ratios* stay
meaningful (a decode tick whose t_memory dominates is bandwidth-bound on
any backend), but predicted floors on tiny CI shapes sit well under the
measured wall because per-dispatch host overhead dominates; see
docs/faq/perf.md "Reading the roofline" for the documented factor.

Everything here is OFF the step path: ``observe()`` is a dict update
under a lock, and the expensive parts (probes, the per-entry AOT cost
analysis) run only inside :func:`peaks` / :func:`summary` — pull-based,
from bench.py, the HTTP endpoint, or an explicit call.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref

from . import telemetry
from .base import getenv, register_env

register_env("MXNET_OBSERVATORY", False,
             "enable the roofline observatory: measured-peak probes + "
             "per-executable MFU/MBU attribution (off = zero overhead, "
             "one attribute read per instrumented site)")
register_env("MXNET_OBSERVATORY_DIR", "",
             "directory for persisted peak-probe results (JSON per "
             "backend/device-kind/device-count, provenance-checked); "
             "unset = probe once per process, no persistence")
register_env("MXNET_OBSERVATORY_PROBE_N", 0,
             "matmul probe dimension override (NxN); 0 = auto per "
             "backend (512 on cpu, 4096 on accelerators)")
register_env("MXNET_OBSERVATORY_PROBE_MB", 64,
             "memcpy/HBM-bandwidth probe buffer size in MiB")

SCHEMA_VERSION = 1

_enabled = bool(getenv("MXNET_OBSERVATORY"))
_lock = threading.Lock()
_lanes = {}          # lane -> {"cache", "key", "wall", "exec", "count"}
_peaks = None        # cached probe result (dict) for this process
_probe_runs = 0      # how many times the probes actually RAN (tests pin
                     # disk-cache hits by asserting this does not move)
_last_summary = None  # last computed summary (snapshot embeds it for free)
_cache = None        # the named CompileCache("observatory") for probes


def enabled():
    return _enabled


def enable(on=True):
    """Turn the observatory on/off at runtime (tests; bench.py calls this
    unless ``MXNET_OBSERVATORY=0``). Enabling never probes by itself —
    peaks are measured lazily on the first :func:`peaks` call."""
    global _enabled
    _enabled = bool(on)
    return _enabled


def disable():
    return enable(False)


def reset(lane=None):
    """Drop observed lane timings (``lane=None`` drops all). bench.py
    resets between phases so one phase's steady-state EWMA never bleeds
    into the next lane's attribution."""
    with _lock:
        if lane is None:
            _lanes.clear()
        else:
            _lanes.pop(lane, None)


# ---------------------------------------------------------------------------
# lane observations (the hot-path API — cheap, no compile, no probe)
# ---------------------------------------------------------------------------

# Lane -> telemetry gauge prefix. "generation.tick" publishes tick_mbu
# (underscore, per the decode-tick metric family), the others dot-join.
_GAUGE_PREFIX = {"step": "step.", "serving": "serving.",
                 "generation.tick": "serving.generation.tick_"}


def _ewma_update(st, field, value, alpha=0.2):
    cur = st.get(field)
    if cur is None:
        st[field] = float(value)
    else:
        st[field] = (1.0 - alpha) * cur + alpha * float(value)
    mn = st.get(field + "_min")
    st[field + "_min"] = float(value) if mn is None else min(mn, float(value))


def observe(lane, cache=None, key=None, wall_s=None, exec_s=None):
    """Record one steady-state timing sample for ``lane``.

    ``cache``/``key`` name the executable that ran (a CompileCache — the
    instance itself, or its name — and entry key; attribution pulls its
    FLOPs/bytes later, NEVER here). Pass the INSTANCE where the call
    site has it: cache names are shared (every GenerationEngine owns a
    ``CompileCache("generation")``, and two engines can hold the same
    decode key for different models), so a name-only lookup can resolve
    to another instance's entry. The instance is held weakly —
    observing never extends an executable's lifetime. ``wall_s`` is the
    full step/tick wall time, ``exec_s`` the window around just the
    executable dispatch+drain (their difference is the host gap). Call
    sites gate on ``observatory._enabled`` so the off cost is one
    attribute read."""
    if not _enabled:
        return
    with _lock:
        st = _lanes.setdefault(lane, {"count": 0})
        if cache is not None:
            if isinstance(cache, str):
                st["cache"] = cache
                st.pop("_cache_ref", None)
            else:
                st["cache"] = cache.name
                st["_cache_ref"] = weakref.ref(cache)
            st["key"] = key
        if wall_s is not None:
            _ewma_update(st, "wall_s", wall_s)
        if exec_s is not None:
            _ewma_update(st, "exec_s", exec_s)
        st["count"] += 1


def lanes():
    """Shallow copy of the observed-lane table (tests/report); private
    fields (the weak cache ref) are stripped."""
    with _lock:
        return {k: {f: v for f, v in st.items() if not f.startswith("_")}
                for k, st in _lanes.items()}


# ---------------------------------------------------------------------------
# measured-peak probes
# ---------------------------------------------------------------------------


def _probe_cache():
    global _cache
    if _cache is None:
        from .compile_cache import CompileCache

        # track_memory=False: three tiny probe programs need no per-entry
        # AOT memory analysis riding the /memory scrape
        _cache = CompileCache("observatory", track_memory=False)
    return _cache


def _provenance():
    import jax

    dev = jax.devices()[0]
    n = int(getenv("MXNET_OBSERVATORY_PROBE_N"))
    backend = dev.platform
    if not n:
        n = 512 if backend == "cpu" else 4096
    return {"schema_version": SCHEMA_VERSION,
            "backend": backend,
            "device_kind": getattr(dev, "device_kind", backend),
            "device_count": jax.device_count(),
            "probe_n": n,
            "probe_mb": int(getenv("MXNET_OBSERVATORY_PROBE_MB")),
            "jax": getattr(jax, "__version__", "unknown")}


def _peaks_path(prov):
    d = getenv("MXNET_OBSERVATORY_DIR")
    if not d:
        return None
    slug = "".join(c if c.isalnum() else "-"
                   for c in str(prov["device_kind"]))[:48]
    return os.path.join(
        d, f"peaks_{prov['backend']}_{slug}_{prov['device_count']}.json")


def _min_time(fn, reps=3):
    best = math.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _probe_matmul_flops(n, dtype):
    import jax
    import jax.numpy as jnp

    a = jnp.ones((n, n), dtype)
    f = _probe_cache().get_or_build(
        ("probe_matmul", n, str(dtype)),
        lambda: jax.jit(lambda x, y: x @ y))
    jax.block_until_ready(f(a, a))  # compile + warm
    dt = _min_time(lambda: jax.block_until_ready(f(a, a)))
    return 2.0 * n ** 3 / max(dt, 1e-9)


def _probe_hbm_bandwidth(mb):
    import jax
    import jax.numpy as jnp

    n = max(int(mb), 1) * (1 << 20) // 4
    x = jnp.ones((n,), jnp.float32)
    f = _probe_cache().get_or_build(
        ("probe_copy", n), lambda: jax.jit(lambda v: v + 1.0))
    jax.block_until_ready(f(x))
    dt = _min_time(lambda: jax.block_until_ready(f(x)))
    # the kernel reads N and writes N bytes — 2x the buffer per pass
    return 2.0 * n * 4 / max(dt, 1e-9)


def _probe_collective_bandwidth(mb):
    """Sustained all-reduce bytes/s per participant over every visible
    device, or None on a single device (nothing to move)."""
    import jax
    import jax.numpy as jnp

    ndev = jax.device_count()
    if ndev < 2:
        return None
    n = max(int(mb), 1) * (1 << 20) // (4 * ndev)
    x = jnp.ones((ndev, n), jnp.float32)
    f = _probe_cache().get_or_build(
        ("probe_psum", ndev, n),
        lambda: jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i"))
    jax.block_until_ready(f(x))
    dt = _min_time(lambda: jax.block_until_ready(f(x)))
    # ring all-reduce moves 2*(N-1)/N of the payload per participant
    moved = 2.0 * (ndev - 1) / ndev * n * 4
    return moved / max(dt, 1e-9)


def _run_probes(prov):
    global _probe_runs
    _probe_runs += 1
    n, mb = prov["probe_n"], prov["probe_mb"]
    flops = {}
    for dtype in ("float32", "bfloat16"):
        try:
            flops[dtype] = _probe_matmul_flops(n, dtype)
        except Exception:  # noqa: BLE001 — a dtype the backend lacks
            pass
    out = {"provenance": prov,
           "matmul_flops": flops,
           "hbm_bytes_per_s": _probe_hbm_bandwidth(mb),
           "collective_bytes_per_s": None,
           "probed_unix": time.time(),
           "source": "measured"}
    try:
        out["collective_bytes_per_s"] = _probe_collective_bandwidth(mb)
    except Exception:  # noqa: BLE001 — collectives are best-effort
        pass
    return out


def peaks(refresh=False):
    """The measured device peaks (probing lazily on first use). The
    result is cached in-process and — when ``MXNET_OBSERVATORY_DIR`` is
    set — on disk, keyed and validated by provenance: a different
    backend, device kind, device count, or probe size re-probes instead
    of trusting a stale file. ``refresh=True`` forces a re-probe."""
    global _peaks
    if _peaks is not None and not refresh:
        return _peaks
    prov = _provenance()
    path = _peaks_path(prov)
    if path and not refresh:
        try:
            with open(path) as f:
                doc = json.load(f)
            if doc.get("provenance") == prov:
                doc["source"] = "disk"
                _peaks = doc
                return _peaks
        except (OSError, ValueError):
            pass
    doc = _run_probes(prov)
    if path:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp~"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass
    _peaks = doc
    return _peaks


def probe_verdict():
    """One-line provenance string for ledgers/sidecars: where the peaks
    came from and what they are."""
    p = _peaks
    if p is None:
        return "unprobed"
    prov = p["provenance"]
    return (f"{p['source']}:{prov['backend']}/{prov['device_kind']}"
            f"x{prov['device_count']}")


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def attribute(flops, bytes_accessed, coll_bytes, pk,
              dtype="float32", wall_s=None, exec_s=None):
    """Pure roofline math over one executable's counted work — the
    hand-checkable core (test_observatory.py pins it against fixtures).
    Returns the attribution row; measured fields are present only when a
    ``wall_s`` observation is supplied."""
    mf = pk.get("matmul_flops") or {}
    peak_flops = mf.get(dtype) or (max(mf.values()) if mf else None)
    hbm = pk.get("hbm_bytes_per_s")
    cbw = pk.get("collective_bytes_per_s")
    t_compute = (flops / peak_flops) if (flops and peak_flops) else 0.0
    t_memory = (bytes_accessed / hbm) if (bytes_accessed and hbm) else 0.0
    t_comm = (coll_bytes / cbw) if (coll_bytes and cbw) else 0.0
    floor = max(t_compute, t_memory, t_comm)
    if floor <= 0.0:
        bound = "unknown"
    elif floor == t_comm:
        bound = "comm"
    elif floor == t_memory:
        bound = "bandwidth"
    else:
        bound = "compute"
    out = {"flops": flops, "bytes_accessed": bytes_accessed,
           "collective_bytes": coll_bytes,
           "t_compute_s": t_compute, "t_memory_s": t_memory,
           "t_comm_s": t_comm,
           "predicted_floor_s": floor, "roofline_bound": bound,
           "peak_flops": peak_flops, "peak_hbm_bytes_per_s": hbm,
           "peak_collective_bytes_per_s": cbw, "dtype": dtype}
    if wall_s and wall_s > 0:
        out["measured_s"] = wall_s
        if peak_flops and flops:
            out["mfu"] = (flops / wall_s) / peak_flops
        if hbm and bytes_accessed:
            out["mbu"] = (bytes_accessed / wall_s) / hbm
        out["comm_fraction"] = (t_comm / floor) if floor > 0 else 0.0
        if floor > 0:
            out["measured_over_floor"] = wall_s / floor
        if exec_s is not None:
            out["host_gap_us"] = max(wall_s - exec_s, 0.0) * 1e6
    return out


def _find_cache(name, key=None):
    """The live CompileCache called ``name`` — preferring, when several
    instances share the name (every GenerationEngine owns a
    ``CompileCache("generation")``), the one that actually holds ``key``."""
    from . import compile_cache

    first = None
    for c in compile_cache.all_caches():
        if c.name == name:
            if key is None or key in getattr(c, "_entry_stats", {}):
                return c
            if first is None:
                first = c
    return first


def _entry_dtype(cache, key):
    """Dominant input dtype of the entry — picks which matmul peak the
    MFU denominator uses (bf16 programs against the bf16 peak)."""
    st = cache._entry_stats.get(key)
    if not st:
        return "float32"
    try:
        import jax

        best, best_bytes = "float32", -1
        args, kwargs = st["avals"]
        for leaf in jax.tree_util.tree_leaves((args, kwargs)):
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                nb = int(leaf.dtype.itemsize)
                for d in leaf.shape:
                    nb *= int(d)
                if nb > best_bytes:
                    best, best_bytes = str(leaf.dtype), nb
        return best
    except Exception:  # noqa: BLE001 — a wrong dtype only blurs the peak
        return "float32"


def attribution(lane, pk=None):
    """Roofline attribution for one observed lane, or None when the lane
    has no observation or no attributable executable. Pull-based: the
    first call per entry pays the shared AOT cost/memory analysis
    (compile_cache.entry_cost — seconds for donated programs), never the
    step path."""
    with _lock:
        st = dict(_lanes.get(lane) or {})
    cache_name, key = st.get("cache"), st.get("key")
    if cache_name is None:
        return None
    # the observed instance itself when still alive; the name lookup is
    # only a fallback (names are shared across instances)
    ref = st.get("_cache_ref")
    cache = ref() if ref is not None else None
    if cache is None:
        cache = _find_cache(cache_name, key)
    if cache is None:
        return None
    cost = cache.entry_cost(key)
    if not cost:
        return None
    coll = cache.entry_collectives(key) or {}
    coll_bytes = sum(v.get("bytes", 0) for v in coll.values())
    # wall falls back to the dispatch window: a caller driving
    # fused_step directly (bench's module loop) observes only exec_s,
    # and the blocked dispatch window IS its wall
    wall = st.get("wall_s")
    if wall is None:
        wall = st.get("exec_s")
    row = attribute(cost.get("flops", 0.0),
                    cost.get("bytes_accessed", 0.0),
                    coll_bytes, pk or peaks(),
                    dtype=_entry_dtype(cache, key),
                    wall_s=wall, exec_s=st.get("exec_s"))
    row["lane"] = lane
    row["cache"] = cache_name
    row["key"] = repr(key)
    row["samples"] = st.get("count", 0)
    mem = cache.entry_memory(key)
    if mem:
        row["peak_bytes"] = mem.get("peak_bytes")
    return row


def _publish_gauges(lane, row):
    prefix = _GAUGE_PREFIX.get(lane, lane + ".")
    for field, gauge in (("mfu", "mfu"), ("mbu", "mbu"),
                        ("comm_fraction", "comm_fraction"),
                        ("host_gap_us", "host_gap_us")):
        v = row.get(field)
        if v is not None:
            telemetry.gauge(prefix + gauge).set(round(float(v), 6))


def summary(refresh_peaks=False):
    """The observatory's full report: measured peaks + one attribution
    row per observed lane, gauges published as a side effect
    (``step.mfu``/``step.mbu``/``serving.*``/``serving.generation.tick_mbu``
    and friends — the SLO plane's MFU-collapse row reads these). This is
    the ``/roofline`` endpoint's body and the bench stamp source."""
    global _last_summary
    if not _enabled:
        return {"enabled": False}
    pk = peaks(refresh=refresh_peaks)
    out = {"enabled": True, "schema_version": SCHEMA_VERSION,
           "probe_verdict": probe_verdict(), "peaks": pk, "lanes": {}}
    for lane in list(lanes()):
        try:
            row = attribution(lane, pk)
        except Exception:  # noqa: BLE001 — one broken lane must not
            continue       # take down the scrape
        if row is None:
            continue
        out["lanes"][lane] = row
        _publish_gauges(lane, row)
    # worst offenders: observed lanes by achieved utilization against
    # their binding roof, ascending — the report's first read
    def util(r):
        return r.get("mbu" if r.get("roofline_bound") == "bandwidth"
                     else "mfu") or 0.0

    out["worst"] = sorted(out["lanes"],
                          key=lambda k: util(out["lanes"][k]))
    _last_summary = out
    return out


def cached_summary():
    """The last computed :func:`summary` (no probes, no AOT work) —
    telemetry.snapshot embeds this so report tooling sees the roofline
    without triggering compilation from a scrape path."""
    return _last_summary
