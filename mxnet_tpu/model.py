"""Model-level helpers: kvstore wiring + checkpointing.

Parity: `python/mxnet/model.py` — `_create_kvstore`:82,
`_update_params_on_kvstore`:150, `_update_params`:162,
`save_checkpoint`:394, `load_checkpoint`:424. (The deprecated FeedForward
class is intentionally not reproduced; `Module` is the supported symbolic
trainer.)
"""
from __future__ import annotations

import os

from . import ndarray as nd
from . import kvstore as kvs

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

from collections import namedtuple

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from --kv-store style input (parity model.py:82)."""
    update_on_kvstore = bool(int(os.getenv("MXNET_UPDATE_ON_KVSTORE", "1")))
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStoreBase):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # one device: updates happen inline; no kvstore needed
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(npy.size for npy in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Push grads, pull updated weights (parity model.py:150)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None,
                   param_names=None):
    """Local updater path (parity model.py:162)."""
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        if dev_updates:
            i, w, g = zip(*dev_updates)
            updater(i, w, g)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Checkpoint: `prefix-symbol.json` + `prefix-####.params`
    (parity model.py:394)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json", remove_amp_cast=remove_amp_cast)
    save_dict = {f"arg:{k}": v.as_in_context(_cpu()) for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v.as_in_context(_cpu()) for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Load a checkpoint (parity model.py:424). Returns (symbol, arg_params,
    aux_params)."""
    from . import symbol as sym
    symbol = None
    json_path = f"{prefix}-symbol.json"
    if os.path.exists(json_path):
        symbol = sym.load(json_path)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params


def _cpu():
    from .context import cpu
    return cpu()
