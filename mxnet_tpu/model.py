"""Model-level helpers: kvstore wiring + checkpointing.

Parity: `python/mxnet/model.py` — `_create_kvstore`:82,
`_update_params_on_kvstore`:150, `_update_params`:162,
`save_checkpoint`:394, `load_checkpoint`:424. (The deprecated FeedForward
class is intentionally not reproduced; `Module` is the supported symbolic
trainer.)
"""
from __future__ import annotations

import os
import re
import time as _time

from . import ndarray as nd
from . import kvstore as kvs
from . import telemetry
from . import tracing
from .base import MXNetError, getenv
from .log import get_logger

__all__ = ["save_checkpoint", "load_checkpoint", "find_latest_checkpoint",
           "list_checkpoint_epochs", "BatchEndParam"]

from collections import namedtuple

# step_stats (defaulted — positional construction stays valid) carries the
# per-step telemetry breakdown dict {data/fwdbwd/update/sync/total ms +
# the step-latency histogram for on-demand p50/p99} from BaseModule.fit to
# batch-end callbacks (Speedometer)
BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals",
                            "step_stats"],
                           defaults=[None])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from --kv-store style input (parity model.py:82)."""
    update_on_kvstore = bool(int(os.getenv("MXNET_UPDATE_ON_KVSTORE", "1")))
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStoreBase):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # one device: updates happen inline; no kvstore needed
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(npy.size for npy in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Push grads, pull updated weights (parity model.py:150).

    Bucketed by default: ONE grouped push + ONE grouped pull for the whole
    parameter set — the store fuses the keys of a grouped call into flat
    per-dtype buckets (O(#buckets) collectives, `dist._push_dense`) instead
    of dispatching one collective per key. `MXNET_GRAD_BUCKETING=0`
    restores the per-key reference loop."""
    from .parallel import grad_sync as _gs

    live = [(i, param_names[i], arg_list, grad_list)
            for i, (arg_list, grad_list)
            in enumerate(zip(param_arrays, grad_arrays))
            if grad_list[0] is not None]
    if not live:
        return
    if _gs.bucketing_enabled():
        names = [n for _, n, _, _ in live]
        prios = [-i for i, _, _, _ in live]
        kvstore.push(names, [g for _, _, _, g in live], priority=prios)
        kvstore.pull(names, [a for _, _, a, _ in live], priority=prios)
        return
    for index, name, arg_list, grad_list in live:
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None,
                   param_names=None):
    """Local updater path (parity model.py:162). The kvstore gradient
    allreduce rides the bucketed `GradSync` scheduler (overlapped
    per-bucket collectives) unless `MXNET_GRAD_BUCKETING=0`. The
    aggregated updater call below engages the ZeRO-1 sharded update when
    `MXNET_ZERO1=1` (`Updater._zero1_call`); checkpointing through
    `save_checkpoint` + updater `get_states` stays format-identical —
    shards are gathered on save and re-sharded on load."""
    live = [i for i, (_, grad_list)
            in enumerate(zip(param_arrays, grad_arrays))
            if grad_list[0] is not None]
    if kvstore and live:
        from .parallel import grad_sync as _gs

        if _gs.bucketing_enabled() and _gs.sync_compatible(kvstore):
            grads = [grad_arrays[i] for i in live]
            # scheduler cached ON the store: this helper is stateless but
            # the bucket plan / persistent flat buffers must survive steps
            sched = getattr(kvstore, "_grad_sync_sched", None)
            if sched is None:
                sched = _gs.GradSync(kvstore)
                kvstore._grad_sync_sched = sched
            sched.configure_from(grads, priorities=[-i for i in live])
            sched.sync(grads)
        else:
            for index in live:
                kvstore.push(param_names[index], grad_arrays[index],
                             priority=-index)
                kvstore.pull(param_names[index], grad_arrays[index],
                             priority=-index)
    updates = [[] for _ in range(num_device)]
    for index in live:
        arg_list, grad_list = param_arrays[index], grad_arrays[index]
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        if dev_updates:
            i, w, g = zip(*dev_updates)
            updater(i, w, g)


def _param_path(prefix, epoch):
    return "%s-%04d.params" % (prefix, epoch)


def list_checkpoint_epochs(prefix):
    """Sorted epoch numbers with an existing `prefix-####.params` file."""
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    pat = re.compile(re.escape(base) + r"-(\d{4,})\.params$")  # %04d grows past epoch 9999
    try:
        entries = os.listdir(d)
    except OSError:
        return []
    return sorted(int(m.group(1)) for m in map(pat.match, entries) if m)


def find_latest_checkpoint(prefix):
    """Newest saved epoch for `prefix`, or None — the resume entry point
    after a preemption (`load_checkpoint(prefix)` uses it implicitly)."""
    epochs = list_checkpoint_epochs(prefix)
    return epochs[-1] if epochs else None


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True, keep=None):
    """Checkpoint: `prefix-symbol.json` + `prefix-####.params`
    (parity model.py:394).

    ``keep`` (or `MXNET_CHECKPOINT_KEEP`) bounds retention: after a
    successful save only the newest ``keep`` epoch files survive — long
    runs stop eating the disk that their own resumability depends on.
    The eviction is ONE engine task ordered after the current epoch's
    write (const var on the new path, mutable vars on every evicted path)
    that first verifies the new file end-to-end (CRC scan), so a save
    that failed or landed torn can never have destroyed the checkpoint a
    resume would fall back to."""
    tele = telemetry._enabled
    t0 = _time.perf_counter() if tele else 0.0
    with tracing.span("checkpoint.save", cat="io", prefix=prefix,
                      epoch=epoch):
        if symbol is not None:
            symbol.save(f"{prefix}-symbol.json",
                        remove_amp_cast=remove_amp_cast)
        save_dict = {f"arg:{k}": v.as_in_context(_cpu())
                     for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v.as_in_context(_cpu())
                          for k, v in aux_params.items()})
        cur_path = _param_path(prefix, epoch)
        nd.save(cur_path, save_dict)
    if tele:
        # caller-visible cost (device fetch + dispatch); the async disk
        # write itself lands in checkpoint.write_us on the engine worker
        telemetry.histogram("checkpoint.save_us").record(
            (_time.perf_counter() - t0) * 1e6)
    keep = getenv("MXNET_CHECKPOINT_KEEP") if keep is None else int(keep)
    if keep > 0:
        from . import engine

        survivors = set(list_checkpoint_epochs(prefix)[-keep:]) | {epoch}
        victims = [_param_path(prefix, old)
                   for old in list_checkpoint_epochs(prefix)
                   if old not in survivors]
        if victims and engine.async_io_enabled():
            engine.push(_evict_old_epochs, victims, cur_path,
                        const_vars=(engine.path_var(cur_path),),
                        mutable_vars=tuple(engine.path_var(p) for p in victims))
        elif victims:
            _evict_old_epochs(victims, cur_path)
    if str(getenv("MXNET_ROLLOUT_DIR") or "").strip():
        # train->serve streaming: every checkpoint also becomes a rollout
        # version (arrays gathered to replicated host copies inside
        # publish); failures never propagate back into the training loop
        from .serving import rollout

        rollout.publish_checkpoint(prefix, epoch, arg_params, aux_params)


def _evict_old_epochs(old_paths, new_path):
    """Remove evicted epoch files, but only after the replacing epoch
    verifies end-to-end (structural + CRC scan — an async write that
    failed leaves an empty placeholder, a torn one fails its footers) —
    never trade the last good checkpoint for an unloadable one."""
    from .ndarray.utils import checkpoint_intact

    if not checkpoint_intact(new_path):
        return
    for p in old_paths:
        try:
            os.remove(p)
        except FileNotFoundError:
            pass


def load_checkpoint(prefix, epoch=None, fallback=None, return_epoch=False):
    """Load a checkpoint (parity model.py:424). Returns (symbol, arg_params,
    aux_params) — plus the epoch actually loaded when ``return_epoch``.

    Resilience extensions: ``epoch=None`` loads the newest saved epoch
    (:func:`find_latest_checkpoint`); when ``fallback`` is true (the
    default in latest mode) a corrupt or torn epoch file — CRC mismatch,
    truncation, vanished file — is logged and the next older epoch is
    tried, so one bad save cannot strand a resumable run. Resume loops
    should pass ``return_epoch=True`` and set ``begin_epoch`` from the
    result: after a fallback the loaded epoch is OLDER than the newest
    file on disk."""
    from . import engine
    from . import symbol as sym
    symbol = None
    json_path = f"{prefix}-symbol.json"
    if os.path.exists(json_path):
        symbol = sym.load(json_path)
    if fallback is None:
        fallback = epoch is None
    if epoch is None:
        epoch = find_latest_checkpoint(prefix)
        if epoch is None:
            raise MXNetError(f"no checkpoints found for prefix {prefix!r}")
    if engine.async_io_enabled():
        # surface pending async IO failures NOW, attributed to the writes
        # that caused them — inside the loop below they would be misread
        # as "this candidate is unreadable" and silently eaten by fallback
        engine.wait_all()
    candidates = [epoch]
    if fallback:
        candidates += [e for e in reversed(list_checkpoint_epochs(prefix))
                       if e < epoch]
    from . import health

    errors = []
    save_dict = None
    loaded_epoch = None
    with tracing.span("checkpoint.load", cat="io", prefix=prefix,
                      epoch=epoch):
        for cand in candidates:
            try:
                save_dict = nd.load(_param_path(prefix, cand))
                loaded_epoch = cand
                break
            except (MXNetError, OSError) as e:
                errors.append(e)
                if not fallback:
                    raise
                if telemetry._enabled:
                    telemetry.counter("checkpoint.crc_fallback").inc()
                    telemetry.counter("checkpoint.corrupt_skipped").inc()
                if health._enabled:
                    health.event("checkpoint_fallback", prefix=str(prefix),
                                 epoch=int(cand), error=repr(e))
                get_logger("mxnet_tpu.model").warning(
                    "checkpoint %s is unreadable (%s); falling back to an "
                    "older epoch", _param_path(prefix, cand), e)
    if save_dict is None:
        raise MXNetError(
            f"no loadable checkpoint for prefix {prefix!r} at or below "
            f"epoch {epoch}: {errors}") from (errors[-1] if errors else None)
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    if return_epoch:
        return symbol, arg_params, aux_params, loaded_epoch
    return symbol, arg_params, aux_params


def _cpu():
    from .context import cpu
    return cpu()
