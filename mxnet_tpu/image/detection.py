"""Detection image pipeline (parity: `python/mxnet/image/detection.py`):
augmenters that transform image AND object boxes together, plus
`ImageDetIter`. Labels follow the reference's detection format: each object
row = [id, xmin, ymin, xmax, ymax, ...extras], coordinates normalized to
[0, 1]."""
from __future__ import annotations

import json
import random as _random

import numpy as _np

from .. import ndarray as nd
from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only augmenter (labels pass through)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _random.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _random.random() < self.p:
            src = nd.array(src.asnumpy()[:, ::-1].copy(), dtype=str(src.dtype))
            label = label.copy()
            tmp = 1.0 - label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by min object coverage (reference
    DetRandomCropAug, simplified candidate sampling)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _crop_label(self, label, x0, y0, w, h):
        out = []
        for row in label:
            cx = (row[1] + row[3]) / 2
            cy = (row[2] + row[4]) / 2
            if not (x0 <= cx <= x0 + w and y0 <= cy <= y0 + h):
                continue
            new = row.copy()
            new[1] = max(0.0, (row[1] - x0) / w)
            new[2] = max(0.0, (row[2] - y0) / h)
            new[3] = min(1.0, (row[3] - x0) / w)
            new[4] = min(1.0, (row[4] - y0) / h)
            out.append(new)
        return _np.asarray(out) if out else None

    def __call__(self, src, label):
        H, W = src.shape[:2]
        for _ in range(self.max_attempts):
            area = _random.uniform(*self.area_range) * W * H
            ratio = _random.uniform(*self.aspect_ratio_range)
            w = int(round((area * ratio) ** 0.5))
            h = int(round((area / ratio) ** 0.5))
            if w > W or h > H:
                continue
            x0 = _random.randint(0, W - w)
            y0 = _random.randint(0, H - h)
            new_label = self._crop_label(label, x0 / W, y0 / H, w / W, h / H)
            if new_label is None:
                continue
            return _img.fixed_crop(src, x0, y0, w, h), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range

    def __call__(self, src, label):
        H, W = src.shape[:2]
        scale = _random.uniform(*self.area_range)
        new_w, new_h = int(W * scale ** 0.5), int(H * scale ** 0.5)
        x0 = _random.randint(0, new_w - W) if new_w > W else 0
        y0 = _random.randint(0, new_h - H) if new_h > H else 0
        canvas = _np.empty((new_h, new_w, src.shape[2]), dtype="uint8")
        canvas[:] = _np.asarray(self.pad_val, dtype="uint8")
        canvas[y0:y0 + H, x0:x0 + W] = src.asnumpy()
        label = label.copy()
        label[:, 1] = (label[:, 1] * W + x0) / new_w
        label[:, 3] = (label[:, 3] * W + x0) / new_w
        label[:, 2] = (label[:, 2] * H + y0) / new_h
        label[:, 4] = (label[:, 4] * H + y0) / new_h
        return nd.array(canvas, dtype="uint8"), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Build the standard detection augmenter list (reference
    CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(_img.ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(_img.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(_img.ColorJitterAug(
            brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(_img.HueJitterAug(hue)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(_img.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(_img.ImageIter):
    """Detection iterator: object labels padded to fixed [N, max_obj, width]
    (reference ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, aug_list=None,
                 data_name="data", label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "pca_noise", "hue",
                         "inter_method")})
        self._det_auglist = aug_list
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         aug_list=[], data_name=data_name,
                         label_name=label_name, **{
                             k: v for k, v in kwargs.items()
                             if k not in ("resize", "rand_crop", "rand_pad",
                                          "rand_gray", "rand_mirror", "mean",
                                          "std", "brightness", "contrast",
                                          "saturation", "pca_noise", "hue",
                                          "inter_method")})
        self._label_width = None

    def _parse_label(self, label):
        """Flat header label → [num_obj, width] array (reference
        _parse_label: [header_width, obj_width, obj...])."""
        raw = _np.asarray(label).ravel()
        header_width = int(raw[0])
        obj_width = int(raw[1])
        body = raw[header_width:]
        n = len(body) // obj_width
        return body[:n * obj_width].reshape(n, obj_width)

    def _decode_augment(self, label, raw):
        img = _img.imdecode(raw)
        objs = self._parse_label(label)
        for aug in self._det_auglist:
            img, objs = aug(img, objs)
        arr = img.asnumpy()
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        return objs, arr.astype("float32")

    def next(self):
        samples = []
        pad = 0
        try:
            for _ in range(self.batch_size):
                samples.append(self.next_sample())
        except StopIteration:
            if not samples:
                raise
            pad = self.batch_size - len(samples)
        decoded = [self._decode_augment(l, r) for l, r in samples]
        while len(decoded) < self.batch_size:
            decoded.append(decoded[0])
        data = _np.stack([d for _, d in decoded])
        max_obj = max(len(l) for l, _ in decoded)
        width = decoded[0][0].shape[1] if len(decoded[0][0]) else 5
        labels = _np.full((self.batch_size, max_obj, width), -1.0, "float32")
        for i, (l, _) in enumerate(decoded):
            if len(l):
                labels[i, :len(l)] = l
        return DataBatch(data=[nd.array(data)], label=[nd.array(labels)],
                         pad=pad)
