"""Image decode/augment pipeline.

Parity: `python/mxnet/image/image.py` (imdecode/imresize/crops/augmenter
classes/`ImageIter`) and the C++ decode path it fronts
(`src/io/iter_image_recordio_2.cc:873` — N decode threads over RecordIO
chunks → imdecode → augmenters; `src/io/image_aug_default.cc`).

TPU-native design: decode+augment stay on HOST (numpy/PIL — the reference
uses OpenCV on host too); a thread pool overlaps per-image work and a
prefetch queue overlaps batch assembly with device compute, the role of the
reference's decode threads + `PrefetcherIter`. Batches reach the device
once, at the jit boundary.
"""
from __future__ import annotations

import io as _io
import logging
import os
import queue as _queue
import random as _random
import threading

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from .. import recordio
from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["imdecode", "imread", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "CreateAugmenter", "ImageIter", "ImageRecordIter"]


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an HWC uint8 NDArray (reference
    image.py imdecode over cv2; PIL here)."""
    from PIL import Image

    if isinstance(buf, nd.NDArray):
        buf = bytes(buf.asnumpy().astype("uint8"))
    img = Image.open(_io.BytesIO(buf))
    if flag == 0:
        img = img.convert("L")
        arr = _np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = _np.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]
    return nd.array(arr.astype("uint8"), dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    from ..resilience import open_checked

    with open_checked(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def _bilinear_resize_np(arr, h, w):
    """Align-corners sample bilinear on uint8 HWC — the SAME arithmetic as
    the native decode workers (`src/imgpipe.cc` resize_bilinear), so
    interp=1 output is identical whether or not the .so is built."""
    sh, sw = arr.shape[:2]
    if (sh, sw) == (h, w):
        return arr.copy()
    ry = (sh - 1) / (h - 1) if h > 1 else 0.0
    rx = (sw - 1) / (w - 1) if w > 1 else 0.0
    fy = _np.arange(h, dtype=_np.float32) * _np.float32(ry)
    fx = _np.arange(w, dtype=_np.float32) * _np.float32(rx)
    y0 = fy.astype(_np.int32)
    x0 = fx.astype(_np.int32)
    y1 = _np.minimum(y0 + 1, sh - 1)
    x1 = _np.minimum(x0 + 1, sw - 1)
    wy = (fy - y0)[:, None, None].astype(_np.float32)
    wx = (fx - x0)[None, :, None].astype(_np.float32)
    a = arr.astype(_np.float32)
    top = a[y0][:, x0] + (a[y0][:, x1] - a[y0][:, x0]) * wx
    bot = a[y1][:, x0] + (a[y1][:, x1] - a[y1][:, x0]) * wx
    return (top + (bot - top) * wy + 0.5).astype(arr.dtype)


def imresize(src, w, h, interp=1):
    """Resize an HWC image NDArray (reference imresize over cv2).

    interp=1 (INTER_LINEAR) uses the repo's own bilinear — bit-identical
    between the python chain and the native decode workers; other interp
    codes map to PIL filters."""
    from PIL import Image

    arr = src.asnumpy() if isinstance(src, nd.NDArray) else _np.asarray(src)
    if int(interp) == 1:
        return nd.array(_bilinear_resize_np(arr.astype("uint8"), h, w)
                        .astype(arr.dtype.name), dtype=arr.dtype.name)
    squeeze = arr.shape[-1] == 1
    img = Image.fromarray(arr[:, :, 0] if squeeze else arr.astype("uint8"))
    resample = {0: Image.NEAREST, 2: Image.BICUBIC,
                3: Image.LANCZOS, 4: Image.LANCZOS}.get(interp, Image.BILINEAR)
    img = img.resize((w, h), resample)
    out = _np.asarray(img)
    if squeeze:
        out = out[:, :, None]
    return nd.array(out.astype(arr.dtype.name), dtype=arr.dtype.name)


def scale_down(src_size, size):
    """Scale `size` down to fit inside src_size keeping aspect (reference)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else src
    out = arr[y0:y0 + h, x0:x0 + w]
    out_nd = nd.array(out, dtype=str(out.dtype))
    if size is not None and (w, h) != size:
        out_nd = imresize(out_nd, *size, interp=interp)
    return out_nd


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _random.randint(0, w - new_w)
    y0 = _random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (inception-style; reference
    random_size_crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _random.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(_random.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _random.randint(0, w - new_w)
            y0 = _random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = src.asnumpy().astype("float32") if isinstance(src, nd.NDArray) else src
    arr = arr - _np.asarray(mean)
    if std is not None:
        arr = arr / _np.asarray(std)
    return nd.array(arr)


# --------------------------------------------------------------------------
# augmenters
# --------------------------------------------------------------------------


class Augmenter:
    """Image augmenter base (reference image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, nd.NDArray):
                kwargs[k] = v.asnumpy().tolist()
            elif isinstance(v, _np.ndarray):
                kwargs[k] = v.tolist()

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, *self.size, interp=self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.random() < self.p:
            return nd.array(src.asnumpy()[:, ::-1].copy(),
                            dtype=str(src.dtype))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.array(src.asnumpy().astype(self.typ), dtype=self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = _np.asarray(mean) if mean is not None else None
        self.std = _np.asarray(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _random.uniform(-self.brightness, self.brightness)
        return nd.array(src.asnumpy().astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _random.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype("float32")
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        mean = gray.mean() * (1.0 - alpha)
        return nd.array(arr * alpha + mean)


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _random.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype("float32")
        gray = (arr * self._coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return nd.array(arr * alpha + gray)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], "float32")
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], "float32")

    def __call__(self, src):
        alpha = _random.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], "float32")
        t = self.ityiq @ bt @ self.tyiq
        arr = src.asnumpy().astype("float32")
        return nd.array(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA (AlexNet-style) lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, "float32")
        self.eigvec = _np.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,)).astype("float32")
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd.array(src.asnumpy().astype("float32") + rgb)


class RandomGrayAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.random() < self.p:
            arr = src.asnumpy().astype("float32")
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return nd.array(_np.broadcast_to(gray, arr.shape).copy())
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference image.py
    CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and (std is not None or std is None):
        if isinstance(mean, (tuple, list)):
            mean = _np.asarray(mean)
        if isinstance(std, (tuple, list)):
            std = _np.asarray(std)
        if mean is not None:
            auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# --------------------------------------------------------------------------
# ImageIter — threaded decode+augment from RecordIO or image lists
# --------------------------------------------------------------------------


class ImageIter(DataIter):
    """Image iterator with RecordIO (.rec) or imglist backends, a decode
    thread pool and output prefetching (reference image.py ImageIter; the
    threaded pipeline role of `iter_image_recordio_2.cc:873`)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", num_threads=4, **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        assert dtype in ("int32", "float32", "int64", "float64"), dtype
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self._num_threads = max(1, int(num_threads))

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgidx or (os.path.splitext(path_imgrec)[0] + ".idx")
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys) if hasattr(self.imgrec, "keys") \
                    else sorted(self.imgrec.idx.keys())
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
                assert not shuffle, "shuffle needs a .idx file"
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = _np.array(parts[1:-1], dtype=dtype)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = sorted(self.imglist.keys())
            self.path_root = path_root
        else:
            self.imglist = {}
            for i, (label, fname) in enumerate(imglist):
                self.imglist[i] = (_np.array(label, ndmin=1, dtype=dtype), fname)
            self.seq = list(self.imglist.keys())
            self.path_root = path_root

        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            n_per = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n_per:(part_index + 1) * n_per]

        aug_kwargs = {k: v for k, v in kwargs.items()
                      if k in ("resize", "rand_crop", "rand_resize",
                               "rand_mirror", "mean", "std", "brightness",
                               "contrast", "saturation", "hue", "pca_noise",
                               "rand_gray", "inter_method")}
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **aug_kwargs)
        else:
            self.auglist = aug_list
        # native decode workers (src/imgpipe.cc; reference
        # iter_image_recordio_2.cc:873): taken when the augmenter chain is
        # exactly the standard resize/crop/mirror/normalize set this C++
        # path implements — any exotic augmenter keeps the python chain
        self._native_cfg = None
        # the C++ resize is bilinear (INTER_LINEAR): when a resize happens
        # the native path is taken only for inter_method=1, so pixels never
        # silently depend on whether the .so is built (python's default is
        # inter_method=2, bicubic)
        interp_ok = (not aug_kwargs.get("resize")) or \
            int(aug_kwargs.get("inter_method", 2)) == 1
        if aug_list is None and tuple(data_shape)[0] == 3 and interp_ok and \
                not any(aug_kwargs.get(k) for k in
                        ("rand_resize", "brightness", "contrast",
                         "saturation", "hue", "pca_noise", "rand_gray")):
            from .. import lib as _lib

            pipe = _lib.native_imgpipe(self._num_threads)
            if pipe is not None:
                mean = aug_kwargs.get("mean")
                std = aug_kwargs.get("std")
                if mean is True:
                    mean = _np.array([123.68, 116.28, 103.53])
                if std is True:
                    std = _np.array([58.395, 57.12, 57.375])
                self._native_cfg = {
                    "pipe": pipe,
                    "resize": int(aug_kwargs.get("resize", 0) or 0),
                    "rand_crop": bool(aug_kwargs.get("rand_crop", False)),
                    "rand_mirror": bool(aug_kwargs.get("rand_mirror", False)),
                    "mean": mean if mean is not None else None,
                    "std": std if std is not None else None,
                }

        self.provide_data = [DataDesc(data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(label_name, (batch_size, label_width)
                                       if label_width > 1 else (batch_size,))]
        self.last_batch_handle = last_batch_handle
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            _random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Return (label, raw image bytes or path)."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                label = header.label
                return label, img
            label, fname = self.imglist[idx]
            path = os.path.join(self.path_root, fname) if self.path_root else fname
            with open(path, "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _decode_batch_native(self, samples):
        """One GIL-free C call decodes+augments the whole batch
        (`src/imgpipe.cc`); None -> fall back to the python chain (e.g. a
        record that is not a JPEG)."""
        raws = []
        for _, raw in samples:
            if not isinstance(raw, (bytes, bytearray)) or \
                    not bytes(raw[:2]) == b"\xff\xd8":
                return None  # not a JPEG: python path handles it
            raws.append(bytes(raw))
        cfg = self._native_cfg
        from .. import random as _rand

        out, failed = cfg["pipe"].decode_batch(
            raws, self.data_shape[1], self.data_shape[2],
            resize_short=cfg["resize"], rand_crop=cfg["rand_crop"],
            rand_mirror=cfg["rand_mirror"], seed=_rand.derive_host_seed(),
            mean=cfg["mean"], std=cfg["std"], nthreads=self._num_threads)
        if out is None:
            return None
        for i in failed:  # re-decode ONLY the natively-undecodable records
            _, arr = self._decode_augment(*samples[i])
            out[i] = arr
        return [(label, arr) for (label, _), arr in zip(samples, out)]

    def _decode_augment(self, label, raw):
        img = imdecode(raw)
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy()
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)  # HWC → CHW
        return label, arr.astype("float32")

    def next(self):
        from concurrent.futures import ThreadPoolExecutor

        samples = []
        pad = 0
        try:
            for _ in range(self.batch_size):
                samples.append(self.next_sample())
        except StopIteration:
            if not samples:
                raise
            if self.last_batch_handle == "discard":
                raise StopIteration
            pad = self.batch_size - len(samples)

        decoded = None
        if self._native_cfg is not None:
            decoded = self._decode_batch_native(samples)
        if decoded is None:
            if self._num_threads > 1 and len(samples) > 1:
                if not hasattr(self, "_pool"):
                    self._pool = ThreadPoolExecutor(self._num_threads)
                decoded = list(self._pool.map(
                    lambda s: self._decode_augment(*s), samples))
            else:
                decoded = [self._decode_augment(*s) for s in samples]

        while len(decoded) < self.batch_size:  # pad by repeating the first
            decoded.append(decoded[0])

        data = _np.stack([d for _, d in decoded])
        labels = _np.stack([_np.array(l, ndmin=1) for l, _ in decoded])
        if self.label_width == 1:
            labels = labels[:, 0]
        return DataBatch(data=[nd.array(data)], label=[nd.array(labels)],
                         pad=pad)


def ImageRecordIter(path_imgrec, data_shape, batch_size, label_width=1,
                    shuffle=False, aug_list=None, preprocess_threads=4,
                    prefetch_buffer=2, **kwargs):
    """RecordIO image iterator + background prefetch: the python-native
    rendering of the reference's registered `ImageRecordIter`
    (`iter_image_recordio_2.cc:873` decode threads + `iter_prefetcher.h`)."""
    from ..io.io import PrefetchingIter

    base = ImageIter(batch_size, data_shape, label_width=label_width,
                     path_imgrec=path_imgrec, shuffle=shuffle,
                     aug_list=aug_list, num_threads=preprocess_threads,
                     **kwargs)
    return PrefetchingIter(base, prefetch_depth=prefetch_buffer)
