"""Repo-local sitecustomize: the axon register() guard.

Takes effect when this directory precedes /root/.axon_site on
PYTHONPATH (`PYTHONPATH=/root/repo:/root/.axon_site python ...`) — at
site-import time only PYTHONPATH entries are on sys.path (the script
dir is prepended AFTER site runs, verified empirically), so THIS module
then shadows the axon sitecustomize that registers the TPU PJRT plugin
at interpreter start. tools/tpu_watcher.sh and the TPU operator sweep
launch their children this way.

Why shadow it: the axon relay has repeatedly entered a half-wedged state
(accepting connections, never answering — BENCH_NOTES_r05.md) in which
that register() call blocks EVERY python process before main() runs:
bench.py, the test suite, the multichip dryrun — none of them can even
start, and no in-script timeout can help because the hang happens before
the script executes. This wrapper execs the original axon sitecustomize
under a SIGALRM deadline and continues CPU-only when the relay is
wedged, turning an infinite hang into a bounded delay plus the existing
CPU-fallback paths.

Behavior:
- PALLAS_AXON_POOL_IPS unset        -> nothing to do (axon's own no-op).
- JAX_PLATFORMS contains "cpu"      -> skip register entirely (a
  CPU-pinned process must not touch the relay; same rule as
  tests/conftest.py stripping the variable for children).
- otherwise                         -> exec the axon sitecustomize with a
  MXNET_AXON_REGISTER_TIMEOUT-second alarm (default 120; 0 disables the
  guard). On timeout: warn and continue without the TPU backend.
"""
import os
import signal
import sys
import time

_AXON_SITE = "/root/.axon_site/sitecustomize.py"


class _RegisterTimeout(BaseException):
    # BaseException: the exec'd axon code wraps register() in a broad
    # `except Exception`, which must NOT be able to swallow the deadline
    pass


def _load_axon():
    if not os.path.exists(_AXON_SITE):
        return
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        return

    try:
        timeout = int(os.environ.get("MXNET_AXON_REGISTER_TIMEOUT", "120"))
    except ValueError:
        # a malformed value must degrade to the default, not silently skip
        # loading the axon site for every process in the environment
        print("[sitecustomize] malformed MXNET_AXON_REGISTER_TIMEOUT "
              f"{os.environ.get('MXNET_AXON_REGISTER_TIMEOUT')!r}; "
              "using 120s", file=sys.stderr)
        timeout = 120
    # the exec'd code does `from axon.register import register`; that
    # package lives inside /root/.axon_site, which may sit BEHIND this
    # directory on sys.path (or be absent if PYTHONPATH was rewritten)
    axon_dir = os.path.dirname(_AXON_SITE)
    if axon_dir not in sys.path:
        sys.path.append(axon_dir)
    with open(_AXON_SITE) as f:
        code = compile(f.read(), _AXON_SITE, "exec")
    glb = {"__name__": "sitecustomize_axon", "__file__": _AXON_SITE}

    use_alarm = timeout > 0 and hasattr(signal, "SIGALRM")
    if not use_alarm:
        try:
            exec(code, glb)
        except Exception as e:  # noqa: BLE001 — never take the interpreter down
            print(f"[sitecustomize] axon site failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
        return

    def _on_alarm(signum, frame):
        raise _RegisterTimeout()

    old = signal.signal(signal.SIGALRM, _on_alarm)
    armed_at = time.monotonic()
    # signal.alarm returns the seconds REMAINING of any alarm the embedding
    # process had already armed — that countdown must be restored below,
    # not silently cancelled by our cleanup
    prev_remaining = signal.alarm(timeout)
    try:
        exec(code, glb)
    except _RegisterTimeout:
        print(
            f"[sitecustomize] axon register() exceeded {timeout}s "
            "(relay wedged?); continuing without the TPU backend",
            file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — never take the interpreter down
        print(f"[sitecustomize] axon site failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        if prev_remaining:
            # re-arm the pre-existing countdown, less the time we consumed
            # (floored at 1s: the embedder's deadline has effectively
            # passed and should fire promptly, not be dropped)
            elapsed = int(time.monotonic() - armed_at)
            signal.alarm(max(1, prev_remaining - elapsed))


_load_axon()
